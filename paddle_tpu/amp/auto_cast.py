"""AMP autocast.

Reference: imperative AMP lists (paddle/fluid/imperative/amp_auto_cast.h:38-66,
AutoCastInputs O1 / CastPureFp16Inputs O2) and python amp/auto_cast.py.

TPU-native: bf16 is the default low precision (no loss scaling needed);
fp16 kept for parity. O1 casts inputs of allow-listed ops; O2 runs the whole
region in low precision except block-listed ops. Implemented as a context
that installs a cast policy consulted by core.tensor.apply via an op-name
filter wrapper around the nn functional layer.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor

# Ops whose inputs are cast to low precision in O1 (MXU-bound ops).
# `embedding` is here so the activation stream STARTS in bf16: with the
# table gathered low-precision, every downstream residual add / dropout /
# norm rides bf16 HBM traffic instead of f32 (the norms keep f32 internal
# stats — see layer_norm in nn/functional.py).
white_list = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "scaled_dot_product_attention", "einsum", "embedding",
    # the model zoo's fused matmul-class ops (GPT/BERT/ERNIE attention
    # projections and LM heads) — without these the attention branch of
    # the residual stream silently rides f32 under O1
    "fused_qkv", "attn_out", "mlm_head", "ernie_mlm_head", "lm_logits",
}

# Ops kept in fp32 even under O2 (numerically sensitive). `layer_norm`
# and `batch_norm` are deliberately absent: both compute statistics in
# f32 internally and return the input dtype (batch_norm folds to one
# bf16 multiply-add in the conv epilogue), so casting their inputs up
# would only double activation bandwidth — on ResNet-50 the old
# blacklisted batch_norm cost ~40 ms/step in convert/copy traffic. The
# f32 EMA buffers are safe either way: the running-stat update consumes
# the f32 statistics, never the low-precision activations. group/
# instance norm keep the conservative listing (unfused normalizers).
black_list = {
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "group_norm", "instance_norm", "norm",
    "mean", "sum", "exp", "log", "logsumexp", "erf", "erfinv", "pow",
    "cumsum", "rsqrt", "sqrt", "square",
}

# Never cast, at ANY level: the op preserves its inputs' dtypes and runs
# f32 statistics internally; a blanket cast would also hit its f32 state
# buffers (see _cast_target). fused_conv_bn resolves the conv-operand cast
# itself (nn/functional.py) so its f32 EMA buffers ride through untouched.
_keep_dtype = {"batch_norm", "fused_conv_bn"}

_tls = threading.local()


class _AmpState:
    __slots__ = ("enabled", "dtype", "level", "custom_white", "custom_black",
                 "wl", "bl")

    def __init__(self, enabled, dtype, level, custom_white, custom_black):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.custom_white = custom_white or set()
        self.custom_black = custom_black or set()
        # effective lists resolved ONCE per context (the custom lists are
        # fixed for the state's lifetime; per-op set unions would sit on
        # the hot eager dispatch path)
        self.wl = (white_list | self.custom_white) - self.custom_black
        self.bl = black_list | self.custom_black


def amp_state():
    return getattr(_tls, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast analogue (bf16-first on TPU)."""
    prev = amp_state()
    _tls.amp = _AmpState(enable, dtypes.convert_dtype(dtype), level,
                         set(custom_white_list or []), set(custom_black_list or []))
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast


def _cast_target(op_name: str, st):
    """The ONE policy resolver: target jnp dtype for op inputs, or None
    (leave dtypes alone). Both the actual cast and the cache token derive
    from this, so they can never desynchronize."""
    if st is None or not st.enabled:
        return None
    if op_name in _keep_dtype and op_name not in st.custom_black \
            and op_name not in st.custom_white:
        # dtype-preserving ops: casting would hit EVERY float input —
        # including batch_norm's f32 running-stat buffers, whose EMA
        # write-back must never round through bf16. The op handles its
        # own internal precision (f32 stats, input-dtype application).
        # An EXPLICIT custom listing overrides the default (the user's
        # debugging knob keeps working).
        return None
    if st.level == "O2":
        return jnp.float32 if op_name in st.bl else st.dtype
    if op_name in st.wl:
        return st.dtype
    if op_name in st.bl:
        return jnp.float32
    return None


def amp_target_dtype(op_name: str):
    """Dispatch-layer hook: the cast-target dtype STRING for this op
    under the active policy, or None. Resolved once at op-dispatch time —
    the value (not the thread-local state) is captured by any deferred
    trace, so a backward jitted outside the autocast context still
    replays the forward's policy."""
    target = _cast_target(op_name, amp_state())
    return None if target is None else str(jnp.dtype(target))


from ..core.tensor import set_amp_target_hook  # noqa: E402

set_amp_target_hook(amp_target_dtype)


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to low precision, keep fp32 master
    weights inside the optimizer (reference: amp/auto_cast.py decorate)."""
    d = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=d)
        if optimizers is not None:
            opts = [optimizers] if not isinstance(optimizers, (list, tuple)) else optimizers
            for o in opts:
                o._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers
