"""Dynamic loss scaling.

Reference: python/paddle/amp/grad_scaler.py → fluid AmpScaler
(fluid/dygraph/amp/loss_scaler.py:40) built on the
``check_finite_and_unscale`` + ``update_loss_scaling`` ops
(operators/amp/*.cc). Here both ops are jnp reductions fused by XLA.

On TPU bf16 training usually runs unscaled; the scaler exists for fp16
parity and returns fast when disabled.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._skip_count = 0         # optimizer steps skipped on inf grads
        self._unscaled: set = set()  # ids of optimizers unscaled this step
        self._stepped: set = set()   # ids of optimizers stepped this step

    def is_enable(self):
        return self._enable

    @property
    def found_inf(self) -> bool:
        """Whether the LAST unscale found non-finite gradients (the step
        about to be / just skipped). The NaN watchdog
        (monitor.numerics.NaNWatchdog) consults this to tell 'dynamic
        loss scaling doing its job' from a real numerics failure."""
        return self._found_inf

    @property
    def skip_count(self) -> int:
        """Total optimizer steps skipped because gradients were
        non-finite (mirrored into the monitor registry as
        ``amp_skipped_steps_total``)."""
        return self._skip_count

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """check_finite_and_unscale over the optimizer's param grads.

        Guarded against double-unscaling within one step (reference:
        amp/grad_scaler.py:198 checks OptimizerState before unscaling), so
        the documented ``unscale_ -> clip -> step`` pattern divides by the
        loss scale exactly once.
        """
        if not self._enable:
            self._found_inf = False
            return
        if id(optimizer) in self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update().")
        self._unscaled.add(id(optimizer))
        params = [p for p in optimizer._ensure_params() if p.grad is not None]
        if not params:
            self._found_inf = False
            return
        inv = 1.0 / self._scale
        finite = True
        for p in params:
            g = p.grad._data * inv
            p.grad._data = g
        # one fused finiteness reduction
        flat = [jnp.sum(jnp.isfinite(p.grad._data).astype(jnp.int32) == 0)
                for p in params]
        bad = sum(np.asarray(f) for f in flat)
        self._found_inf = bool(bad > 0)

    def minimize(self, optimizer, scaled_loss):
        """backward + step + scale update in one call (reference:
        amp/grad_scaler.py:123 minimize — which DOES advance the scale,
        unlike step())."""
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        """Unscale (if not already) and conditionally optimizer.step().
        Does NOT advance the loss scale — call update() after, per the
        reference pattern scale().backward(); step(opt); update()
        (reference: amp/grad_scaler.py:159 — raises on double step)."""
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) in self._stepped:
            raise RuntimeError(
                "step() has already been called since the last update().")
        if id(optimizer) not in self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        else:
            self._note_skip()
        self._stepped.add(id(optimizer))

    def _note_skip(self):
        """A skipped optimizer step (inf/nan grads): count locally and in
        the metrics registry so the AMP skip rate shows up next to the
        NaN-watchdog trips in monitor reports."""
        self._skip_count += 1
        try:
            from ..monitor import get_registry
            get_registry().counter(
                "amp_skipped_steps_total",
                "optimizer steps skipped by GradScaler on non-finite "
                "gradients").inc()
        except Exception:
            pass

    def update(self):
        self._unscaled.clear()
        self._stepped.clear()
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    # -- functional API for jitted steps -----------------------------------
    def unscale_and_check(self, grads: dict):
        """Pure: returns (unscaled_grads, found_inf) for use inside jit."""
        inv = 1.0 / self._scale
        unscaled = {k: g * inv for k, g in grads.items()}
        flat = [jnp.all(jnp.isfinite(g)) for g in unscaled.values()]
        finite = jnp.stack(flat).all()
        return unscaled, ~finite

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable,
                "skip_count": self._skip_count}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._skip_count = state.get("skip_count", 0)


class GradScaler(AmpScaler):
    """Public API name (reference: amp/grad_scaler.py:26)."""
