"""paddle.device namespace (reference: python/paddle/device.py —
set_device/get_device/is_compiled_with_* plus the cuda sub-namespace).

TPU-native: devices resolve through jax; CUDA-named entry points map to
the accelerator so reference scripts run unchanged."""

from __future__ import annotations

from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace, Place, TPUPlace,
    XPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cuda",
           "is_compiled_with_tpu", "cuda"]


def get_all_device_type():
    import jax
    kinds = {d.platform for d in jax.devices()}
    return sorted(kinds | {"cpu"})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


class cuda:
    """paddle.device.cuda shims: 'cuda' means the attached accelerator."""

    @staticmethod
    def device_count() -> int:
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()

    @staticmethod
    def empty_cache():
        pass          # XLA owns HBM; nothing to release eagerly

    @staticmethod
    def max_memory_allocated(device=None) -> int:
        import jax
        try:
            stats = jax.devices()[0].memory_stats() or {}
            return int(stats.get("peak_bytes_in_use", 0))
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None) -> int:
        import jax
        try:
            stats = jax.devices()[0].memory_stats() or {}
            return int(stats.get("bytes_in_use", 0))
        except Exception:
            return 0
