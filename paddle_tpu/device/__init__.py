"""paddle.device namespace (reference: python/paddle/device.py —
set_device/get_device/is_compiled_with_* plus the cuda sub-namespace).

TPU-native: devices resolve through jax; CUDA-named entry points map to
the accelerator so reference scripts run unchanged."""

from __future__ import annotations

from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace, Place, TPUPlace,
    XPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cuda",
           "is_compiled_with_tpu", "cuda"]


def get_all_device_type():
    import jax
    kinds = {d.platform for d in jax.devices()}
    return sorted(kinds | {"cpu"})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


class cuda:
    """paddle.device.cuda shims: 'cuda' means the attached accelerator.

    Memory accounting routes through :mod:`paddle_tpu.monitor.memory`
    (``device_memory_stats``) — the same plumbing the per-program HBM
    budgets and ``memory_summary()`` use. All functions degrade to 0 on
    backends that publish no allocator stats (``memory_stats()`` is None
    on CPU), matching the reference's CPU behavior.
    """

    # reset_max_memory_allocated watermarks per device id: XLA's peak
    # counter is monotonic with no reset API, so the shim remembers the
    # peak at reset time and reports a fresh high-water mark only when
    # the raw peak has since moved past it (best-effort; in-window peaks
    # below the old one are unobservable from the runtime's counters).
    _peak_baseline: dict = {}

    @staticmethod
    def _stats(device=None):
        from ..monitor.memory import device_memory_stats
        return device_memory_stats(cuda._resolve(device))

    @staticmethod
    def _resolve(device=None):
        import jax
        try:
            if device is None:
                return jax.devices()[0]
            if isinstance(device, int):
                return jax.devices()[device]
            return device
        except Exception:
            return None

    @staticmethod
    def device_count() -> int:
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()

    @staticmethod
    def empty_cache():
        pass          # XLA owns HBM; nothing to release eagerly

    @staticmethod
    def memory_allocated(device=None) -> int:
        stats = cuda._stats(device)
        return int((stats or {}).get("bytes_in_use", 0))

    @staticmethod
    def max_memory_allocated(device=None) -> int:
        stats = cuda._stats(device)
        if not stats:
            return 0
        peak = int(stats.get("peak_bytes_in_use", 0))
        dev = cuda._resolve(device)
        base = cuda._peak_baseline.get(getattr(dev, "id", 0))
        if base is None:
            return peak
        if peak > base:
            return peak
        return int(stats.get("bytes_in_use", 0))

    @staticmethod
    def reset_max_memory_allocated(device=None) -> None:
        """Start a fresh peak-memory window (reference:
        ``paddle.device.cuda.reset_max_memory_allocated``)."""
        stats = cuda._stats(device)
        dev = cuda._resolve(device)
        cuda._peak_baseline[getattr(dev, "id", 0)] = \
            int((stats or {}).get("peak_bytes_in_use", 0))

    @staticmethod
    def memory_reserved(device=None) -> int:
        """Bytes the runtime holds from the system for this device (>=
        allocated); falls back to bytes_in_use where the backend keeps
        no separate pool counter."""
        stats = cuda._stats(device)
        if not stats:
            return 0
        for k in ("bytes_reserved", "pool_bytes", "bytes_in_use"):
            if k in stats:
                return int(stats[k])
        return 0

    @staticmethod
    def max_memory_reserved(device=None) -> int:
        stats = cuda._stats(device)
        if not stats:
            return 0
        for k in ("peak_bytes_reserved", "peak_pool_bytes",
                  "peak_bytes_in_use"):
            if k in stats:
                return int(stats[k])
        return 0
