"""Probability distributions.

reference parity: python/paddle/distribution.py — Distribution(:42),
Uniform(:169), Normal(:391), Categorical(:641) with
sample/entropy/log_prob/probs/kl_divergence and tensor-or-scalar
parameter broadcasting.

TPU-native: parameters live as Tensors, sampling draws keys from the
global generator (trace-scoped keys under jit via make_rng), and every
density computation is a tape-aware jnp composition.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.random import make_rng
from .core.tensor import Tensor, apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_tensor(v, dtype=jnp.float32):
    """Keep Tensor params on the tape (grads flow to loc/scale/logits);
    wrap scalars/arrays as constant Tensors."""
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v, dtype))


def _arr(v, dtype=jnp.float32):
    return v._data.astype(dtype) if isinstance(v, Tensor) \
        else jnp.asarray(v, dtype)


class Distribution:
    """Abstract base (reference: distribution.py:42)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference: distribution.py:169)."""

    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape)
        u = jax.random.uniform(key, shape)
        # reparameterized: grads flow to low/high through the tape
        return apply(lambda lo, hi: lo + u * (hi - lo), self.low, self.high,
                     name="uniform_sample")

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply(f, value, self.low, self.high,
                     name="uniform_log_prob")

    def probs(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, 1.0 / (hi - lo), 0.0)
        return apply(f, value, self.low, self.high, name="uniform_probs")

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high,
                     name="uniform_entropy")


class Normal(Distribution):
    """N(loc, scale) (reference: distribution.py:391)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)
        z = jax.random.normal(key, shape)
        # reparameterization trick: pathwise grads to loc/scale
        return apply(lambda mu, sig: mu + z * sig, self.loc, self.scale,
                     name="normal_sample")

    def log_prob(self, value):
        def f(v, mu, sig):
            var = sig * sig
            return (-((v - mu) ** 2) / (2.0 * var)
                    - jnp.log(sig) - 0.5 * math.log(2.0 * math.pi))
        return apply(f, value, self.loc, self.scale,
                     name="normal_log_prob")

    def probs(self, value):
        def f(v, mu, sig):
            var = sig * sig
            return jnp.exp(-((v - mu) ** 2) / (2.0 * var)) / \
                jnp.sqrt(2.0 * math.pi * var)
        return apply(f, value, self.loc, self.scale, name="normal_probs")

    def entropy(self):
        return apply(
            lambda mu, sig: (0.5 + 0.5 * math.log(2.0 * math.pi)
                             + jnp.log(sig) + jnp.zeros_like(mu)),
            self.loc, self.scale, name="normal_entropy")

    def kl_divergence(self, other: "Normal"):
        """KL(self || other) (reference: distribution.py:596)."""
        def f(mu0, sig0, mu1, sig1):
            var_ratio = (sig0 / sig1) ** 2
            t1 = ((mu0 - mu1) / sig1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))
        return apply(f, self.loc, self.scale, other.loc, other.scale,
                     name="normal_kl")


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference:
    distribution.py:641 — parameterized by ``logits``, probs derived)."""

    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        logits = self.logits._data
        return Tensor(jax.random.categorical(
            key, logits, shape=tuple(shape) + logits.shape[:-1]))

    def entropy(self):
        def f(lg):
            p = jax.nn.softmax(lg, axis=-1)
            return -jnp.sum(p * jax.nn.log_softmax(lg, axis=-1), axis=-1)
        return apply(f, self.logits, name="categorical_entropy")

    def kl_divergence(self, other: "Categorical"):
        def f(lg, lh):
            p = jax.nn.softmax(lg, axis=-1)
            return jnp.sum(p * (jax.nn.log_softmax(lg, axis=-1)
                                - jax.nn.log_softmax(lh, axis=-1)), axis=-1)
        return apply(f, self.logits, other.logits, name="categorical_kl")

    @staticmethod
    def _gather(table, ids):
        if table.ndim == 1:                  # single distribution, any batch
            return table[ids]
        return jnp.take_along_axis(table, ids[..., None], axis=-1)[..., 0]

    def probs(self, value):
        ids = _arr(value, jnp.int32)
        return apply(
            lambda lg: self._gather(jax.nn.softmax(lg, axis=-1), ids),
            self.logits, name="categorical_probs")

    def log_prob(self, value):
        ids = _arr(value, jnp.int32)
        return apply(
            lambda lg: self._gather(jax.nn.log_softmax(lg, axis=-1), ids),
            self.logits, name="categorical_log_prob")


# ---------------------------------------------------------------------------
# Breadth beyond the reference's three (reference ships exactly
# Uniform/Normal/Categorical at v2.1, python/paddle/distribution.py;
# SURVEY §7.9 asks to surpass — these follow the same conventions:
# Tensor params on the tape, reparameterized sampling where it exists)
# ---------------------------------------------------------------------------


class Bernoulli(Distribution):
    """Bernoulli(probs)."""

    def __init__(self, probs, name=None):
        self.probs_param = _as_tensor(probs)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        shape = tuple(shape) + self.probs_param._data.shape
        u = jax.random.uniform(key, shape)
        return apply(lambda p: (u < p).astype(jnp.float32),
                     self.probs_param, name="bernoulli_sample")

    def log_prob(self, value):
        def f(v, p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply(f, _as_tensor(value), self.probs_param,
                     name="bernoulli_log_prob")

    def entropy(self):
        def f(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply(f, self.probs_param, name="bernoulli_entropy")

    def kl_divergence(self, other: "Bernoulli"):
        def f(p, q):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            q = jnp.clip(q, 1e-7, 1 - 1e-7)
            return (p * (jnp.log(p) - jnp.log(q))
                    + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))
        return apply(f, self.probs_param, other.probs_param,
                     name="bernoulli_kl")


class Multinomial(Distribution):
    """Multinomial(total_count, probs): counts over K categories."""

    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = _as_tensor(probs)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        p = self.probs_param._data
        draws = jax.random.categorical(
            key, jnp.log(p), shape=tuple(shape) + (self.total_count,)
            + p.shape[:-1])
        counts = jax.nn.one_hot(draws, p.shape[-1]).sum(
            axis=len(tuple(shape)))
        return Tensor(counts)

    def log_prob(self, value):
        def f(v, p):
            logp = jnp.log(jnp.clip(p, 1e-12, None))
            return (jax.lax.lgamma(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(jax.lax.lgamma(v + 1.0), axis=-1)
                    + jnp.sum(v * logp, axis=-1))
        return apply(f, _as_tensor(value), self.probs_param,
                     name="multinomial_log_prob")


class Beta(Distribution):
    """Beta(alpha, beta)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _as_tensor(alpha)
        self.beta = _as_tensor(beta)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        a, b = self.alpha._data, self.beta._data
        shape = tuple(shape) + jnp.broadcast_shapes(a.shape, b.shape)
        return Tensor(jax.random.beta(key, a, b, shape))

    def log_prob(self, value):
        def f(v, a, b):
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return apply(f, _as_tensor(value), self.alpha, self.beta,
                     name="beta_log_prob")

    def mean(self):
        return apply(lambda a, b: a / (a + b), self.alpha, self.beta,
                     name="beta_mean")

    def entropy(self):
        def f(a, b):
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            dg = jax.lax.digamma
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply(f, self.alpha, self.beta, name="beta_entropy")


class Dirichlet(Distribution):
    """Dirichlet(concentration)."""

    def __init__(self, concentration, name=None):
        self.concentration = _as_tensor(concentration)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        c = self.concentration._data
        return Tensor(jax.random.dirichlet(key, c,
                                           tuple(shape) + c.shape[:-1]))

    def log_prob(self, value):
        def f(v, c):
            lnorm = (jnp.sum(jax.lax.lgamma(c), axis=-1)
                     - jax.lax.lgamma(jnp.sum(c, axis=-1)))
            return jnp.sum((c - 1) * jnp.log(v), axis=-1) - lnorm
        return apply(f, _as_tensor(value), self.concentration,
                     name="dirichlet_log_prob")

    def entropy(self):
        def f(c):
            K = c.shape[-1]
            c0 = jnp.sum(c, axis=-1)
            lnorm = (jnp.sum(jax.lax.lgamma(c), axis=-1)
                     - jax.lax.lgamma(c0))
            dg = jax.lax.digamma
            return (lnorm + (c0 - K) * dg(c0)
                    - jnp.sum((c - 1) * dg(c), axis=-1))
        return apply(f, self.concentration, name="dirichlet_entropy")


def kl_divergence(p: Distribution, q: Distribution):
    """Dispatching KL(p || q) (the paddle.distribution.kl_divergence
    surface; defers to the distributions' own pairwise formulas)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__}) "
            "is only defined between same-family distributions here")
    return p.kl_divergence(q)


__all__ += ["Bernoulli", "Multinomial", "Beta", "Dirichlet",
            "kl_divergence"]
