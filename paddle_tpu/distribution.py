"""Probability distributions.

reference parity: python/paddle/distribution.py — Distribution(:42),
Uniform(:169), Normal(:391), Categorical(:641) with
sample/entropy/log_prob/probs/kl_divergence and tensor-or-scalar
parameter broadcasting.

TPU-native: parameters live as Tensors, sampling draws keys from the
global generator (trace-scoped keys under jit via make_rng), and every
density computation is a tape-aware jnp composition.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.random import make_rng
from .core.tensor import Tensor, apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_tensor(v, dtype=jnp.float32):
    """Keep Tensor params on the tape (grads flow to loc/scale/logits);
    wrap scalars/arrays as constant Tensors."""
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v, dtype))


def _arr(v, dtype=jnp.float32):
    return v._data.astype(dtype) if isinstance(v, Tensor) \
        else jnp.asarray(v, dtype)


class Distribution:
    """Abstract base (reference: distribution.py:42)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference: distribution.py:169)."""

    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape)
        u = jax.random.uniform(key, shape)
        # reparameterized: grads flow to low/high through the tape
        return apply(lambda lo, hi: lo + u * (hi - lo), self.low, self.high,
                     name="uniform_sample")

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply(f, value, self.low, self.high,
                     name="uniform_log_prob")

    def probs(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, 1.0 / (hi - lo), 0.0)
        return apply(f, value, self.low, self.high, name="uniform_probs")

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high,
                     name="uniform_entropy")


class Normal(Distribution):
    """N(loc, scale) (reference: distribution.py:391)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)
        z = jax.random.normal(key, shape)
        # reparameterization trick: pathwise grads to loc/scale
        return apply(lambda mu, sig: mu + z * sig, self.loc, self.scale,
                     name="normal_sample")

    def log_prob(self, value):
        def f(v, mu, sig):
            var = sig * sig
            return (-((v - mu) ** 2) / (2.0 * var)
                    - jnp.log(sig) - 0.5 * math.log(2.0 * math.pi))
        return apply(f, value, self.loc, self.scale,
                     name="normal_log_prob")

    def probs(self, value):
        def f(v, mu, sig):
            var = sig * sig
            return jnp.exp(-((v - mu) ** 2) / (2.0 * var)) / \
                jnp.sqrt(2.0 * math.pi * var)
        return apply(f, value, self.loc, self.scale, name="normal_probs")

    def entropy(self):
        return apply(
            lambda mu, sig: (0.5 + 0.5 * math.log(2.0 * math.pi)
                             + jnp.log(sig) + jnp.zeros_like(mu)),
            self.loc, self.scale, name="normal_entropy")

    def kl_divergence(self, other: "Normal"):
        """KL(self || other) (reference: distribution.py:596)."""
        def f(mu0, sig0, mu1, sig1):
            var_ratio = (sig0 / sig1) ** 2
            t1 = ((mu0 - mu1) / sig1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))
        return apply(f, self.loc, self.scale, other.loc, other.scale,
                     name="normal_kl")


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference:
    distribution.py:641 — parameterized by ``logits``, probs derived)."""

    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)

    def sample(self, shape: Sequence[int] = (), seed=0):
        key = jax.random.key(seed) if seed else make_rng("distribution")
        logits = self.logits._data
        return Tensor(jax.random.categorical(
            key, logits, shape=tuple(shape) + logits.shape[:-1]))

    def entropy(self):
        def f(lg):
            p = jax.nn.softmax(lg, axis=-1)
            return -jnp.sum(p * jax.nn.log_softmax(lg, axis=-1), axis=-1)
        return apply(f, self.logits, name="categorical_entropy")

    def kl_divergence(self, other: "Categorical"):
        def f(lg, lh):
            p = jax.nn.softmax(lg, axis=-1)
            return jnp.sum(p * (jax.nn.log_softmax(lg, axis=-1)
                                - jax.nn.log_softmax(lh, axis=-1)), axis=-1)
        return apply(f, self.logits, other.logits, name="categorical_kl")

    @staticmethod
    def _gather(table, ids):
        if table.ndim == 1:                  # single distribution, any batch
            return table[ids]
        return jnp.take_along_axis(table, ids[..., None], axis=-1)[..., 0]

    def probs(self, value):
        ids = _arr(value, jnp.int32)
        return apply(
            lambda lg: self._gather(jax.nn.softmax(lg, axis=-1), ids),
            self.logits, name="categorical_probs")

    def log_prob(self, value):
        ids = _arr(value, jnp.int32)
        return apply(
            lambda lg: self._gather(jax.nn.log_softmax(lg, axis=-1), ids),
            self.logits, name="categorical_log_prob")
