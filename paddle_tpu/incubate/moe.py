"""Mixture-of-Experts: expert-parallel primitives + a gated MoE layer.

reference parity: distributed/utils.py global_scatter(:57)/global_gather
(:151) over the global_scatter/global_gather ops
(operators/collective/global_scatter_op.cc — all-to-all by per-expert
counts). The reference ships ONLY those primitives ("ops only, no python
MoE layer yet", SURVEY §2.3); the MoELayer here completes the story.

TPU-native design: the layer is the GShard formulation — top-k gating,
fixed expert capacity, dispatch/combine as one-hot einsums — so the whole
thing is ONE jit-compilable dense program with static shapes. Expert
weights carry PartitionSpecs over the 'ep' ("expert parallel") mesh axis;
under a mesh, XLA partitions the expert dimension and inserts the
all-to-alls the reference's global_scatter performs explicitly. The
functional global_scatter/global_gather (shard_map + lax.all_to_all) are
provided for reference-style explicit routing.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor, apply
from ..nn.layer import Layer, LayerList

__all__ = ["global_scatter", "global_gather", "top2_gating", "ExpertFFN",
           "MoELayer"]

EP_AXIS = "ep"


def _check_uniform_counts(counts, what: str, total: Optional[int] = None):
    """The static-shape all_to_all only implements the uniform-counts case
    (GShard fixed capacity). Variable per-expert counts — the reference's
    general global_scatter semantics — would silently mis-route rows here,
    so reject them loudly instead."""
    if counts is None:
        return
    import numpy as np
    if isinstance(counts, Tensor):
        counts = counts._data
    if isinstance(counts, jax.core.Tracer):
        # Inside shard_map/jit the counts arrive as tracers whose values
        # cannot be inspected; uniformity is then the caller's contract
        # (the tiled all_to_all silently assumes it). Concrete counts —
        # the eager reference-parity call — are validated below.
        return
    arr = np.asarray(counts)
    if arr.size and not (arr == arr.flat[0]).all():
        raise NotImplementedError(
            f"global_scatter/global_gather: non-uniform {what} "
            f"{arr.tolist()} is unsupported — the TPU lowering is a tiled "
            "all_to_all which requires equal rows per expert (GShard "
            "capacity discipline); pad every expert to the same count")
    if total is not None and arr.size and int(arr.sum()) != int(total):
        raise ValueError(
            f"global_scatter/global_gather: {what} sums to {int(arr.sum())} "
            f"but x has {int(total)} rows — the tiled all_to_all moves "
            "rows/ep_size rows per rank, so the counts must describe "
            "exactly the rows present")


def global_scatter(x, local_count, global_count, group=None):
    """Send rows of ``x`` to experts on other ranks (call inside shard_map
    over the ep axis; reference: distributed/utils.py:57).

    local_count[i]: rows this rank sends to global expert i;
    global_count[i]: rows this rank receives for its local experts.
    Counts must be equal-per-rank (fixed capacity) for the static-shape
    all-to-all — the GShard capacity discipline; non-uniform counts raise.
    """
    from jax import lax
    rows = x.shape[0]
    _check_uniform_counts(local_count, "local_count", total=rows)
    _check_uniform_counts(global_count, "global_count", total=rows)
    n = lax.psum(1, EP_AXIS)
    if rows % n:
        raise ValueError(f"rows {rows} must divide ep size {n}")
    return lax.all_to_all(x, EP_AXIS, split_axis=0, concat_axis=0,
                          tiled=True)


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter (reference: distributed/utils.py:151)."""
    from jax import lax
    rows = x.shape[0]
    _check_uniform_counts(local_count, "local_count", total=rows)
    _check_uniform_counts(global_count, "global_count", total=rows)
    return lax.all_to_all(x, EP_AXIS, split_axis=0, concat_axis=0,
                          tiled=True)


def top2_gating(logits, capacity: int):
    """GShard top-2 gating over raw arrays.

    logits: [S, E] -> (combine [S, E, C], dispatch bool [S, E, C],
    aux_loss). Fixed capacity C per expert; overflow tokens are dropped
    (their combine weights are zero), the standard TPU-shape discipline.
    """
    S, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-1
    idx1 = jnp.argmax(probs, axis=-1)                         # [S]
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    # top-2: best of the rest
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    # positions within each expert's capacity (running count per expert)
    pos1 = (jnp.cumsum(mask1, axis=0) - 1.0) * mask1          # [S, E]
    pos2 = (jnp.cumsum(mask2, axis=0) - 1.0 + mask1.sum(0)) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = (probs * keep1).sum(-1)                              # [S]
    g2 = (probs * keep2).sum(-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    loc1 = jax.nn.one_hot((pos1.sum(-1)).astype(jnp.int32), capacity,
                          dtype=jnp.float32)                  # [S, C]
    loc2 = jax.nn.one_hot((pos2.sum(-1)).astype(jnp.int32), capacity,
                          dtype=jnp.float32)
    combine = (g1[:, None, None] * keep1[:, :, None] * loc1[:, None, :]
               + g2[:, None, None] * keep2[:, :, None] * loc2[:, None, :])
    dispatch = combine > 0.0

    # load-balance aux loss (GShard eq.4): E * mean(frac_tokens * frac_prob)
    frac_tokens = mask1.mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return combine, dispatch, aux


class ExpertFFN(Layer):
    """E homogeneous FFN experts as STACKED parameters [E, ...] with
    P('ep', ...) specs — the GSPMD expert-parallel formulation: a mesh
    with an 'ep' axis places one expert group per slice and the expert
    einsum partitions over it (XLA inserts the all-to-alls)."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation=None):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden))
        self.w1.spec = P(EP_AXIS, None, None)
        self.b1 = self.create_parameter((num_experts, 1, d_hidden),
                                        is_bias=True)
        self.b1.spec = P(EP_AXIS, None, None)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model))
        self.w2.spec = P(EP_AXIS, None, None)
        self.b2 = self.create_parameter((num_experts, 1, d_model),
                                        is_bias=True)
        self.b2.spec = P(EP_AXIS, None, None)
        self.activation = activation

    def forward(self, x):
        """x: [E, C, D] (per-expert capacity slices) -> [E, C, D]."""
        act = self.activation

        def fn(a, w1, b1, w2, b2):
            h = jnp.einsum("ecd,edh->ech", a, w1) + b1
            h = jax.nn.gelu(h) if act is None else act(h)
            return jnp.einsum("ech,ehd->ecd", h, w2) + b2

        return apply(fn, x, self.w1, self.b1, self.w2, self.b2,
                     name="expert_ffn")


class MoELayer(Layer):
    """Gated mixture of experts (completes the reference's MoE primitives).

    Two expert forms:
    - ``experts=ExpertFFN(...)`` (or num_experts+d_hidden kwargs): stacked
      parameters with P('ep', ...) specs — REAL expert parallelism over a
      mesh 'ep' axis, experts applied in one einsum.
    - ``experts=[Layer, ...]``: arbitrary heterogeneous experts applied in
      a python loop; parameters are replicated (no ep sharding) — the
      flexible single-slice form.
    `aux_loss` holds the load-balancing term after each call.
    """

    def __init__(self, d_model: int, experts=None, gate=None,
                 capacity_factor: float = 2.0, num_experts: int = None,
                 d_hidden: int = None, name=None):
        super().__init__()
        self.d_model = d_model
        if experts is None:
            if not (num_experts and d_hidden):
                raise ValueError("pass experts= or num_experts+d_hidden")
            experts = ExpertFFN(num_experts, d_model, d_hidden)
        if isinstance(experts, ExpertFFN):
            self.experts = experts
            self.num_experts = experts.num_experts
            self._stacked = True
        else:
            self.experts = experts if isinstance(experts, LayerList) \
                else LayerList(list(experts))
            self.num_experts = len(self.experts)
            self._stacked = False
        from ..nn.layers.common import Linear
        self.gate = gate or Linear(d_model, self.num_experts, bias_attr=False)
        self.capacity_factor = capacity_factor
        self.aux_loss: Optional[Tensor] = None

    def _capacity(self, tokens: int) -> int:
        return max(4, int(math.ceil(
            tokens * self.capacity_factor / self.num_experts)))

    def forward(self, x):
        B, S, D = x.shape
        tokens = B * S
        C = self._capacity(tokens)
        E = self.num_experts

        flat = x.reshape((tokens, D))
        logits = self.gate(flat)                              # [T, E]

        def gating(lg):
            return top2_gating(lg, C)

        combine, dispatch, aux = apply(gating, logits, name="moe_gating")
        self.aux_loss = aux

        # dispatch: [T, E, C] x [T, D] -> [E, C, D]
        def dispatch_fn(disp, ff):
            return jnp.einsum("tec,td->ecd", disp.astype(ff.dtype), ff)

        expert_in = apply(dispatch_fn, dispatch, flat, name="moe_dispatch")

        # each expert on its capacity slice
        if self._stacked:
            expert_out = self.experts(expert_in)              # [E, C, D]
        else:
            outs = []
            for e, expert in enumerate(self.experts):
                outs.append(expert(expert_in[e]))             # [C, D]
            from ..tensor.manipulation import stack
            expert_out = stack(outs, axis=0)                  # [E, C, D]

        def combine_fn(comb, eo):
            return jnp.einsum("tec,ecd->td", comb.astype(eo.dtype), eo)

        out = apply(combine_fn, combine, expert_out, name="moe_combine")
        return out.reshape((B, S, D))
