"""Incubating ops (reference: python/paddle/incubate/).

softmax_mask_fuse* are plain jnp compositions — XLA fuses mask+softmax into
surrounding matmuls on TPU, which is the entire point of the reference's
hand-fused CUDA kernels (incubate/operators/softmax_mask_fuse_upper_triangle.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle", "moe",
           "LookAhead", "ModelAverage", "optimizer"]

from .optimizer import LookAhead, ModelAverage  # noqa: E402


def __getattr__(name):
    if name == "moe":
        import importlib
        return importlib.import_module(".moe", __name__)
    raise AttributeError(
        f"module 'paddle_tpu.incubate' has no attribute {name!r}")


def softmax_mask_fuse(x, mask, name=None):
    import jax
    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                 name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    import jax

    def _fn(a):
        S = a.shape[-1]
        causal = jnp.tril(jnp.ones((S, S), bool))
        masked = jnp.where(causal, a, -1e30)
        return jax.nn.softmax(masked, axis=-1)

    return apply(_fn, x, name="softmax_mask_fuse_upper_triangle")
