"""Incubate optimizers: LookAhead and ModelAverage.

reference parity: python/paddle/incubate/optimizer/lookahead.py
(LookAhead:25 — slow/fast weights, slow += alpha*(fast-slow) every k
steps) and python/paddle/incubate/optimizer/modelaverage.py
(ModelAverage:29 — sum/accumulator windows with apply()/restore()).

TPU-native: both are pure pytree updates over the wrapped optimizer's
parameter list — no program rewrite; the slow-weight/average state lives
host-side per parameter and the blends run as single fused jnp ops.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k steps forward, 1 step back (Zhang et al. 2019; reference:
    incubate/optimizer/lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not isinstance(k, int) or k <= 0:
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow: Dict[int, jnp.ndarray] = {}
        self._k_count = 0

    # delegate the Optimizer surface to the wrapped optimizer
    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, lr):
        return self.inner_optimizer.set_lr(lr)

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def step(self):
        params = [p for p in (self.inner_optimizer._parameter_list or [])]
        for p in params:
            if id(p) not in self._slow:
                # COPY: the inner optimizer's fused step donates param
                # buffers, which would invalidate an aliased snapshot
                self._slow[id(p)] = jnp.array(p._data, copy=True)
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            a = self.alpha
            for p in params:
                slow = self._slow[id(p)]
                slow = slow + a * (p._data - slow)
                p._data = slow                       # fast snaps to slow
                # keep an independent buffer: p's copy will be donated
                self._slow[id(p)] = jnp.array(slow, copy=True)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        import numpy as np
        out = self.inner_optimizer.state_dict()
        out["lookahead_k_count"] = self._k_count
        for i, p in enumerate(self.inner_optimizer._parameter_list or []):
            if id(p) in self._slow:
                out[f"lookahead_slow{i}"] = np.asarray(self._slow[id(p)])
        return out

    def set_state_dict(self, state):
        self._k_count = int(state.pop("lookahead_k_count", 0))
        for i, p in enumerate(self.inner_optimizer._parameter_list or []):
            key = f"lookahead_slow{i}"
            if key in state:
                self._slow[id(p)] = jnp.asarray(state.pop(key))
        self.inner_optimizer.set_state_dict(state)


class ModelAverage(Optimizer):
    """Running average of parameters applied at eval time (reference:
    incubate/optimizer/modelaverage.py).

    Usage: call step() (or let the training optimizer do its own step and
    call `model_average.step()` after it), then evaluate inside
    `with model_average.apply(): ...`; weights restore on exit.
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._sum: Dict[int, jnp.ndarray] = {}
        self._num: Dict[int, int] = {}
        # previous window (reference keeps sum_1/sum_2 tiers so the
        # average still spans the last full window right after a restart)
        self._old_sum: Dict[int, jnp.ndarray] = {}
        self._old_num: Dict[int, int] = {}
        self._updates = 0
        self._backup: Optional[Dict[int, jnp.ndarray]] = None

    def step(self):
        self._updates += 1
        for p in self._ensure_params():
            if id(p) not in self._sum:
                self._sum[id(p)] = jnp.zeros_like(p._data)
                self._num[id(p)] = 0
                self._old_sum[id(p)] = jnp.zeros_like(p._data)
                self._old_num[id(p)] = 0
            n = self._num[id(p)]
            threshold = min(self.max_window,
                            max(self.min_window,
                                int(self.avg_rate * self._updates) or 1))
            if n >= threshold:
                # roll the window: current becomes old, restart current
                self._old_sum[id(p)] = self._sum[id(p)]
                self._old_num[id(p)] = n
                self._sum[id(p)] = jnp.zeros_like(p._data)
                n = 0
            self._sum[id(p)] = self._sum[id(p)] + p._data
            self._num[id(p)] = n + 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap in averaged weights (reference: ModelAverage.apply)."""
        self._backup = {}
        for p in self._ensure_params():
            if self._num.get(id(p), 0) == 0:
                continue
            self._backup[id(p)] = p._data
            total = self._sum[id(p)] + self._old_sum[id(p)]
            count = self._num[id(p)] + self._old_num[id(p)]
            p._data = (total / count).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup:
            for p in self._ensure_params():
                if id(p) in self._backup:
                    p._data = self._backup[id(p)]
        self._backup = None
