"""MoE routing: capacity-disciplined top-k gating over raw arrays.

One router serves BOTH dispatch implementations (``dispatch.py``): the
einsum oracle and the sort path consume the same per-(token, choice)
decisions — expert index, capacity position, keep mask, normalized gate —
so capacity clipping and drop decisions are identical by construction and
the ``FLAGS_moe_dispatch`` kill switch changes only the data movement.

Math (GShard eq. 2-4 / Switch Transformer §2.2):

- probabilities: softmax over experts in f32 — the router is ALWAYS f32
  even when the activation stream is bf16 (a half-precision router
  misroutes near ties and destabilizes the aux losses);
- top-k selection: iterated argmax with the chosen expert masked out
  (k = 1 is Switch, k = 2 is GShard);
- capacity positions: running per-expert count in token order, choice-
  major priority — ALL first choices take capacity slots before any
  second choice (the GShard discipline); a (token, choice) pair whose
  position overflows ``capacity`` is dropped (its gate contributes 0);
- gate weights: per-token renormalized over the SURVIVING choices;
- aux loss (load balance, GShard eq. 4): E * Σ_e mean_t(top1_mask_e) *
  mean_t(prob_e);
- router z-loss (ST-MoE, Zoph et al. 2022): mean_t(logsumexp_e(logits)²)
  — keeps router logits small so the f32 softmax stays well-conditioned.

All outputs are f32 (integer-valued fields included): the eager tape
synthesizes zero cotangents for unused outputs by output dtype, so a
differentiable multi-output op must stay float-dtyped end to end; the
dispatch fns cast indices to int32 internally.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["Routing", "topk_routing", "top2_gating", "moe_capacity",
           "STATS_FIELDS", "stats_fields"]

#: layout of the per-layer router-stats vector ``Routing.stats``
#: (prefix; followed by the E per-expert load shares): drop_frac = dropped
#: (token, choice) assignments / (T*k); entropy = mean token routing
#: entropy in nats; balance_frac = 1 - total-variation distance of the
#: kept-assignment load from uniform (1.0 = perfectly balanced).
STATS_FIELDS = ("drop_frac", "entropy", "balance_frac")


def stats_fields(num_experts: int):
    """Field names of a stats vector for E experts."""
    return list(STATS_FIELDS) + [f"load_{e}" for e in range(num_experts)]


class Routing(NamedTuple):
    """Per-(choice, token) routing decisions, all f32, choice-major.

    ``gates``/``idx``/``pos``/``keep``: [k, T]; ``aux``/``z``: scalars;
    ``stats``: [len(STATS_FIELDS) + E].
    """
    gates: jax.Array
    idx: jax.Array
    pos: jax.Array
    keep: jax.Array
    aux: jax.Array
    z: jax.Array
    stats: jax.Array


def moe_capacity(tokens: int, capacity_factor: float,
                 num_experts: int) -> int:
    """Fixed per-expert capacity: ceil(T * cf / E), floored at 4 (the
    GShard/Switch convention; tiny batches still give every expert a
    non-degenerate slot count)."""
    return max(4, int(math.ceil(tokens * capacity_factor / num_experts)))


def topk_routing(logits, top_k: int, capacity: int) -> Routing:
    """Route ``logits`` [T, E] to ``top_k`` experts with fixed capacity.

    Raw-array function (call inside ``apply``/jit). For ``top_k == 2``
    the selection/position/gate arithmetic reproduces the legacy
    ``top2_gating`` bit for bit — that function is now a thin wrapper.
    """
    T, E = logits.shape
    if not 1 <= top_k <= E:
        raise ValueError(f"top_k={top_k} outside [1, {E}]")
    lf = logits.astype(jnp.float32)
    probs = jax.nn.softmax(lf, axis=-1)

    masks, idxs = [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)                     # [T]
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # [T, E]
        idxs.append(idx)
        masks.append(m)
        p = p * (1.0 - m)

    # capacity positions: token order within each expert, choice-major
    # priority (choice i's tokens claim slots after every choice < i)
    pos_scalar, keeps = [], []
    offset = None                                        # [E] running count
    for m in masks:
        base = jnp.cumsum(m, axis=0) - 1.0
        pm = (base if offset is None else base + offset) * m
        keeps.append(m * (pm < capacity))
        pos_scalar.append(pm.sum(-1))                    # [T]
        offset = m.sum(0) if offset is None else offset + m.sum(0)

    gates = [(probs * kp).sum(-1) for kp in keeps]       # [T] each
    denom = gates[0]
    for g in gates[1:]:
        denom = denom + g
    denom = jnp.maximum(denom, 1e-9)
    gates = [g / denom for g in gates]

    # aux loss (GShard eq. 4) over the TOP-1 assignment
    frac_tokens = masks[0].mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    # router z-loss (ST-MoE): squared logsumexp of the raw logits
    z = jnp.mean(jax.nn.logsumexp(lf, axis=-1) ** 2)

    # routing-health stats
    kept_e = keeps[0].sum(0)
    for kp in keeps[1:]:
        kept_e = kept_e + kp.sum(0)                      # [E]
    total_kept = kept_e.sum()
    load = kept_e / jnp.maximum(total_kept, 1.0)
    drop_frac = 1.0 - total_kept / float(T * top_k)
    entropy = jnp.mean(-jnp.sum(probs * jnp.log(probs + 1e-9), axis=-1))
    balance = 1.0 - 0.5 * jnp.sum(jnp.abs(load - 1.0 / E))
    stats = jnp.concatenate([
        jnp.stack([drop_frac, entropy, balance]), load]).astype(jnp.float32)

    keep_scalar = [jnp.minimum(kp.sum(-1), 1.0) for kp in keeps]
    return Routing(
        gates=jnp.stack(gates).astype(jnp.float32),
        idx=jnp.stack(idxs).astype(jnp.float32),
        pos=jnp.stack(pos_scalar).astype(jnp.float32),
        keep=jnp.stack(keep_scalar).astype(jnp.float32),
        aux=aux, z=z, stats=stats)


def top2_gating(logits, capacity: int):
    """GShard top-2 gating -> (combine [T, E, C], dispatch bool [T, E, C],
    aux_loss). Legacy surface kept for parity consumers; the combine/
    dispatch tensors are built from :func:`topk_routing`'s decisions with
    the original arithmetic (``dispatch.combine_tensor``)."""
    from .dispatch import combine_tensor
    r = topk_routing(logits, 2, capacity)
    combine = combine_tensor(r, logits.shape[1], capacity)
    return combine, combine > 0.0, r.aux
