"""Mixture-of-Experts subsystem (reference: incubate MoE heritage —
global_scatter/global_gather collective ops; SURVEY §2.3).

Promoted from a single-file GShard layer into an expert-parallel
subsystem (ISSUE 10):

- ``routing``  — capacity-disciplined top-k router (f32), aux + z losses,
  routing-health stats;
- ``dispatch`` — the einsum oracle and the sort-based fast path, selected
  by ``FLAGS_moe_dispatch``;
- ``layer``    — :class:`ExpertFFN` / :class:`MoELayer`, the explicit
  shard_map + all_to_all expert-parallel program
  (``FLAGS_moe_expert_parallel``, double-buffered via
  ``FLAGS_moe_a2a_chunks``), router telemetry, and the reference-parity
  ``global_scatter``/``global_gather`` primitives.

See docs/MOE.md for the routing math, dispatch modes, ep-axis layout and
overlap knobs.
"""

from .dispatch import (DISPATCH_MODES, combine_tensor, dispatch_slots,
                       einsum_combine, einsum_dispatch,
                       resolve_dispatch_mode, sort_combine, sort_dispatch)
from .layer import (EP_AXIS, MOE_STATS, ExpertFFN, MoELayer,
                    expert_ffn_apply, global_gather, global_scatter,
                    moe_ep_group, note_moe_fallback, publish_router_stats,
                    reset_moe_stats, resolve_a2a_chunks)
from .routing import (Routing, STATS_FIELDS, moe_capacity, stats_fields,
                      top2_gating, topk_routing)

__all__ = [
    "EP_AXIS", "MOE_STATS", "ExpertFFN", "MoELayer", "Routing",
    "STATS_FIELDS", "DISPATCH_MODES", "combine_tensor", "dispatch_slots",
    "einsum_combine", "einsum_dispatch", "expert_ffn_apply",
    "global_gather", "global_scatter", "moe_capacity", "moe_ep_group",
    "note_moe_fallback", "publish_router_stats", "reset_moe_stats",
    "resolve_a2a_chunks", "resolve_dispatch_mode", "sort_combine",
    "sort_dispatch", "stats_fields", "top2_gating", "topk_routing",
]
