"""MoE dispatch/combine: the einsum oracle and the sort-based fast path.

Two implementations of the SAME data movement — tokens to per-expert
capacity slices and back — selected by ``FLAGS_moe_dispatch``:

``einsum`` (the GShard formulation, the parity oracle / kill switch):
    dispatch = einsum('tec,td->ecd') over a one-hot [T, E, C] mask,
    combine = einsum('tec,ecd->td') over the weighted mask. Simple, but
    both einsums materialize/stream O(T·E·C) tensors — the memory-bound
    shape this module exists to eliminate (every token row is multiplied
    against E·C mask entries that are almost all zero).

``sort`` (default): flatten the (token, choice) pairs CHOICE-MAJOR
    (matching the router's capacity priority), argsort by expert id so
    writes group by destination expert, then one static-shape scatter
    into a [E*C + 1, D] buffer (row E*C = the drop bucket) and one gather
    back. Data moved is O(T·k·D) regardless of E and capacity — at E=8,
    k=2, cf=2 that is ~8x less than the einsum's O(T·E·C·D) stream, and
    the gap grows linearly with E (cost-model attributed in ``bench.py
    --moe``).

Both consume one :class:`~paddle_tpu.incubate.moe.routing.Routing`, so
capacity clipping and drop decisions are identical; outputs agree
bitwise in f32 (pinned in tests/test_moe.py — the combine sums the same
two addends, and IEEE addition is commutative).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.flags import get_flag

__all__ = ["resolve_dispatch_mode", "combine_tensor", "einsum_dispatch",
           "einsum_combine", "sort_dispatch", "sort_combine",
           "dispatch_slots"]

DISPATCH_MODES = ("sort", "einsum")


def resolve_dispatch_mode(explicit=None) -> str:
    """``FLAGS_moe_dispatch`` (kill switch) unless an explicit layer-level
    override is given."""
    mode = str(explicit or get_flag("moe_dispatch") or "sort").lower()
    if mode not in DISPATCH_MODES:
        raise ValueError(
            f"unknown MoE dispatch mode {mode!r}; expected one of "
            f"{DISPATCH_MODES} (FLAGS_moe_dispatch)")
    return mode


# ---------------------------------------------------------------------------
# einsum path (oracle)
# ---------------------------------------------------------------------------

def combine_tensor(r, num_experts: int, capacity: int):
    """The GShard combine weights [T, E, C] from routing decisions —
    the original one-hot arithmetic (g_i * keep_i * loc_i summed over
    choices), kept as the oracle the sort path is pinned against."""
    k = r.gates.shape[0]
    out = None
    for i in range(k):
        m = jax.nn.one_hot(r.idx[i].astype(jnp.int32), num_experts,
                           dtype=jnp.float32)
        keep_full = m * r.keep[i][:, None]
        loc = jax.nn.one_hot(r.pos[i].astype(jnp.int32), capacity,
                             dtype=jnp.float32)
        term = (r.gates[i][:, None, None] * keep_full[:, :, None]
                * loc[:, None, :])
        out = term if out is None else out + term
    return out


def einsum_dispatch(x, r, num_experts: int, capacity: int):
    """x [T, D] -> expert inputs [E, C, D] via the one-hot einsum."""
    dispatch = combine_tensor(r, num_experts, capacity) > 0.0
    return jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)


def einsum_combine(expert_out, r, capacity: int):
    """expert outputs [E, C, D] -> y [T, D] via the weighted einsum."""
    combine = combine_tensor(r, expert_out.shape[0], capacity)
    return jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype),
                      expert_out)


# ---------------------------------------------------------------------------
# sort path
# ---------------------------------------------------------------------------

def dispatch_slots(r, num_experts: int, capacity: int):
    """Flat per-(choice, token) destination slots, choice-major.

    Returns ``(slot [k*T] int32, gate [k*T] f32, tok [k*T] int32)``:
    ``slot = expert * C + position`` for kept pairs, ``E*C`` (the drop
    bucket) otherwise. Kept slots are unique by construction — capacity
    positions are a per-expert running count."""
    k, T = r.idx.shape
    E, C = num_experts, capacity
    idx = r.idx.reshape(k * T).astype(jnp.int32)
    pos = r.pos.reshape(k * T).astype(jnp.int32)
    keep = r.keep.reshape(k * T) > 0.0
    gate = (r.gates.reshape(k * T) * r.keep.reshape(k * T))
    slot = jnp.where(keep, idx * C + pos, E * C).astype(jnp.int32)
    tok = jnp.tile(jnp.arange(T, dtype=jnp.int32), k)
    return slot, gate.astype(jnp.float32), tok


def sort_dispatch(x, r, num_experts: int, capacity: int):
    """x [T, D] -> expert inputs [E, C, D] via argsort-by-expert +
    static-shape scatter. Dropped pairs route to the trailing drop-bucket
    row, which is sliced off."""
    E, C = num_experts, capacity
    slot, _, tok = dispatch_slots(r, E, C)
    # group writes by destination expert (dropped pairs sort last):
    # stable order preserves the router's choice-major token order
    order = jnp.argsort(slot, stable=True)
    buf = jnp.zeros((E * C + 1, x.shape[1]), x.dtype)
    buf = buf.at[slot[order]].set(x[tok[order]])
    return buf[:E * C].reshape(E, C, x.shape[1])


def sort_combine(expert_out, r, capacity: int):
    """expert outputs [E, C, D] -> y [T, D]: one gather per (choice,
    token) pair through the flat slot map, gate-weighted, summed over
    choices. Dropped pairs gather the zero drop-bucket row."""
    E, C, D = expert_out.shape
    k, T = r.idx.shape
    slot, gate, _ = dispatch_slots(r, E, C)
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, D),
         jnp.zeros((1, D), expert_out.dtype)])
    picked = flat[slot] * gate[:, None].astype(expert_out.dtype)
    return picked.reshape(k, T, D).sum(0)
