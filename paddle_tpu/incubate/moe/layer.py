"""Mixture-of-Experts layers: gated MoE with expert parallelism.

reference parity: distributed/utils.py global_scatter(:57)/global_gather
(:151) over the global_scatter/global_gather ops
(operators/collective/global_scatter_op.cc — all-to-all by per-expert
counts). The reference ships ONLY those primitives ("ops only, no python
MoE layer yet", SURVEY §2.3); this subsystem completes the story.

TPU-native design (ISSUE 10):

- ONE router (``routing.py``) feeds TWO dispatch implementations
  (``dispatch.py``): the GShard one-hot einsums (the parity oracle,
  ``FLAGS_moe_dispatch=einsum``) and the default argsort-by-expert
  static-shape gather/scatter path whose data movement is O(T·k·D)
  instead of O(T·E·C·D).
- Expert weights are STACKED [E, ...] leaves with P('ep', ...) specs.
  Without an ep>1 mesh (or where the explicit program cannot compile)
  XLA's GSPMD partitioner handles placement — the *auto* path. With an
  ep>1 mesh and a capable backend, :class:`MoELayer` runs the EXPLICIT
  expert-parallel program: one ``shard_map`` manual over ``ep`` whose
  body routes its local tokens, exchanges capacity chunks with
  ``lax.all_to_all`` (both directions issued OUTSIDE the expert-compute
  chain and double-buffered over ``FLAGS_moe_a2a_chunks`` chunks so the
  async scheduler hides them behind FFN compute — the PR 9 ppermute
  recipe), and combines locally. Eager dispatches of that program run
  under the PR 5 collective watchdog (chaos site ``collective.hang``),
  so a hung expert exchange raises a structured
  ``CollectiveTimeoutError`` instead of stalling the controller.
- Router telemetry is always computed (drop fraction, routing entropy,
  per-expert load shares, balance) and rides ``Routing.stats``; when the
  forward runs eagerly (concrete values) and the monitor is enabled, the
  layer publishes ``moe_router_*`` gauges + the ``moe_dropped_tokens_
  total`` counter; :func:`publish_router_stats` harvests explicitly
  (tools/monitor_report.py --moe renders them).
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.flags import get_flag, matmul_precision
from ...core.tensor import Tensor, apply
from ...nn.layer import Layer, LayerList
from .dispatch import (einsum_combine, einsum_dispatch,
                       resolve_dispatch_mode, sort_combine, sort_dispatch)
from .routing import (Routing, STATS_FIELDS, moe_capacity, topk_routing)

__all__ = ["EP_AXIS", "MOE_STATS", "reset_moe_stats", "note_moe_fallback",
           "global_scatter", "global_gather", "ExpertFFN", "MoELayer",
           "expert_ffn_apply", "publish_router_stats",
           "resolve_a2a_chunks", "moe_ep_group"]

EP_AXIS = "ep"


def resolve_a2a_chunks(local_capacity: int, flag_value=None) -> int:
    """The expert-parallel double-buffer chunk count actually executed:
    ``FLAGS_moe_a2a_chunks`` reduced until the chunk width tiles the
    local capacity. ONE resolution rule shared by ``_ep_program`` and
    the bench's serial all_to_all baseline — the exchange count they
    model must match the exchanges the program issues."""
    chunks = max(1, int(get_flag("moe_a2a_chunks")
                        if flag_value is None else flag_value))
    while local_capacity % chunks:
        chunks -= 1
    return chunks


def moe_ep_group(n: int):
    """The watchdog/telemetry Group naming the ep axis (no ring
    bootstrap). ONE identity shared by the eager expert-parallel
    dispatch guard and TrainStep's step-program guard, so timeout
    attribution for the same expert all_to_all never diverges between
    the two dispatch paths."""
    from ...distributed.collective import Group
    return Group(list(range(n)), gid=-102, axis_name=EP_AXIS)

#: observability (the nn/scan SCAN_STATS convention): explicit
#: expert-parallel program dispatches, auto-path dispatches by mode, and
#: fallbacks (ep>1 mesh present but the explicit program could not run).
MOE_STATS = {"ep_dispatches": 0, "sort_dispatches": 0,
             "einsum_dispatches": 0, "fallbacks": 0}

_FALLBACK_WARNED: set = set()


def reset_moe_stats():
    MOE_STATS["ep_dispatches"] = 0
    MOE_STATS["sort_dispatches"] = 0
    MOE_STATS["einsum_dispatches"] = 0
    MOE_STATS["fallbacks"] = 0
    _FALLBACK_WARNED.clear()


def note_moe_fallback(reason: str, detail: str = "") -> None:
    """An ep>1 mesh is active but the explicit expert-parallel program
    degraded to the GSPMD auto path — same math, no measured all_to_all
    overlap structure. One-time warning per cause + counted (monitor
    mode adds a ``moe_fallback_total`` registry counter)."""
    MOE_STATS["fallbacks"] += 1
    key = (reason, detail)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        warnings.warn(
            f"MoE expert parallelism degraded to the GSPMD auto path "
            f"(reason: {reason}{'; ' + detail if detail else ''}); the "
            "math is unchanged but the explicit all_to_all program does "
            "not run. On XLA:CPU this is expected for meshes with other "
            "nontrivial axes (manual-subgroup collectives); on TPU check "
            "FLAGS_moe_expert_parallel and the mesh axes.",
            RuntimeWarning, stacklevel=3)
    from ...monitor import enabled as _mon_enabled
    if _mon_enabled():
        from ...monitor import get_registry
        get_registry().counter(
            "moe_fallback_total",
            "ep meshes that degraded to the GSPMD auto path, by cause",
        ).inc(reason=reason)


def _check_uniform_counts(counts, what: str, total: Optional[int] = None):
    """The static-shape all_to_all only implements the uniform-counts case
    (GShard fixed capacity). Variable per-expert counts — the reference's
    general global_scatter semantics — would silently mis-route rows here,
    so reject them loudly instead."""
    if counts is None:
        return
    import numpy as np
    if isinstance(counts, Tensor):
        counts = counts._data
    if isinstance(counts, jax.core.Tracer):
        # Inside shard_map/jit the counts arrive as tracers whose values
        # cannot be inspected; uniformity is then the caller's contract
        # (the tiled all_to_all silently assumes it). Concrete counts —
        # the eager reference-parity call — are validated below.
        return
    arr = np.asarray(counts)
    if arr.size and not (arr == arr.flat[0]).all():
        raise NotImplementedError(
            f"global_scatter/global_gather: non-uniform {what} "
            f"{arr.tolist()} is unsupported — the TPU lowering is a tiled "
            "all_to_all which requires equal rows per expert (GShard "
            "capacity discipline); pad every expert to the same count")
    if total is not None and arr.size and int(arr.sum()) != int(total):
        raise ValueError(
            f"global_scatter/global_gather: {what} sums to {int(arr.sum())} "
            f"but x has {int(total)} rows — the tiled all_to_all moves "
            "rows/ep_size rows per rank, so the counts must describe "
            "exactly the rows present")


def global_scatter(x, local_count, global_count, group=None):
    """Send rows of ``x`` to experts on other ranks (call inside shard_map
    over the ep axis; reference: distributed/utils.py:57).

    local_count[i]: rows this rank sends to global expert i;
    global_count[i]: rows this rank receives for its local experts.
    Counts must be equal-per-rank (fixed capacity) for the static-shape
    all-to-all — the GShard capacity discipline; non-uniform counts raise.
    """
    from jax import lax
    rows = x.shape[0]
    _check_uniform_counts(local_count, "local_count", total=rows)
    _check_uniform_counts(global_count, "global_count", total=rows)
    n = lax.psum(1, EP_AXIS)
    if rows % n:
        raise ValueError(f"rows {rows} must divide ep size {n}")
    return lax.all_to_all(x, EP_AXIS, split_axis=0, concat_axis=0,
                          tiled=True)


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter (reference: distributed/utils.py:151)."""
    from jax import lax
    rows = x.shape[0]
    _check_uniform_counts(local_count, "local_count", total=rows)
    _check_uniform_counts(global_count, "global_count", total=rows)
    return lax.all_to_all(x, EP_AXIS, split_axis=0, concat_axis=0,
                          tiled=True)


def expert_ffn_apply(x, w1, b1, w2, b2, act=None):
    """The stacked-expert FFN over raw arrays: [E, C, D] -> [E, C, D].
    Shared by ExpertFFN.forward and the expert-parallel shard_map body
    (which feeds it LOCAL slices [E/n, n*C_chunk, D])."""
    h = jnp.einsum("ecd,edh->ech", x, w1) + b1
    h = jax.nn.gelu(h) if act is None else act(h)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2


class ExpertFFN(Layer):
    """E homogeneous FFN experts as STACKED parameters [E, ...] with
    P('ep', ...) specs — the GSPMD expert-parallel formulation: a mesh
    with an 'ep' axis places one expert group per slice and the expert
    einsum partitions over it (XLA inserts the all-to-alls on the auto
    path; MoELayer's explicit program issues them itself)."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation=None):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden))
        self.w1.spec = P(EP_AXIS, None, None)
        self.b1 = self.create_parameter((num_experts, 1, d_hidden),
                                        is_bias=True)
        self.b1.spec = P(EP_AXIS, None, None)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model))
        self.w2.spec = P(EP_AXIS, None, None)
        self.b2 = self.create_parameter((num_experts, 1, d_model),
                                        is_bias=True)
        self.b2.spec = P(EP_AXIS, None, None)
        self.activation = activation

    def forward(self, x):
        """x: [E, C, D] (per-expert capacity slices) -> [E, C, D]."""
        act = self.activation
        # the token encodes the closure-captured activation by identity
        # (the apply() cache contract): two stacks differing only in
        # activation must never share a cached trace
        return apply(
            lambda a, w1, b1, w2, b2: expert_ffn_apply(a, w1, b1, w2, b2,
                                                       act),
            x, self.w1, self.b1, self.w2, self.b2, name="expert_ffn",
            _cache_token=("expert_ffn", id(self),
                          id(act) if act is not None else None))


class MoELayer(Layer):
    """Gated mixture of experts (completes the reference's MoE primitives).

    Routing: capacity-disciplined top-``top_k`` gating with an ALWAYS-f32
    router (the gate runs outside any autocast region on an f32 view of
    the tokens); ``aux_loss`` (GShard load balance) and ``z_loss``
    (router logit magnitude) hold the per-call loss terms, ``moe_vec``
    the combined [aux, z, drop, entropy, balance, load_0..E-1] f32 vector
    models thread through scan-over-layers.

    Dispatch: ``FLAGS_moe_dispatch`` (or the ``dispatch_mode`` arg)
    selects sort (default) vs the einsum oracle — see ``dispatch.py``.

    Expert forms:
    - ``experts=ExpertFFN(...)`` (or num_experts+d_hidden kwargs): stacked
      parameters with P('ep', ...) specs — REAL expert parallelism; over
      an ep>1 mesh with a capable backend the layer runs the explicit
      shard_map + all_to_all program (``FLAGS_moe_expert_parallel``).
    - ``experts=[Layer, ...]``: arbitrary heterogeneous experts applied in
      a python loop; parameters are replicated — the flexible
      single-slice form.
    """

    def __init__(self, d_model: int, experts=None, gate=None,
                 capacity_factor: float = 2.0, num_experts: int = None,
                 d_hidden: int = None, top_k: int = 2,
                 dispatch_mode: Optional[str] = None, name=None):
        super().__init__()
        self.d_model = d_model
        if experts is None:
            if not (num_experts and d_hidden):
                raise ValueError("pass experts= or num_experts+d_hidden")
            experts = ExpertFFN(num_experts, d_model, d_hidden)
        if isinstance(experts, ExpertFFN):
            self.experts = experts
            self.num_experts = experts.num_experts
            self._stacked = True
        else:
            self.experts = experts if isinstance(experts, LayerList) \
                else LayerList(list(experts))
            self.num_experts = len(self.experts)
            self._stacked = False
        from ...nn.layers.common import Linear
        self._default_gate = gate is None
        self.gate = gate or Linear(d_model, self.num_experts,
                                   bias_attr=False)
        self.capacity_factor = capacity_factor
        self.top_k = int(top_k)
        if dispatch_mode is not None:
            resolve_dispatch_mode(dispatch_mode)     # validate eagerly
        self.dispatch_mode = dispatch_mode
        self._label = name or "moe"
        # per-call outputs live under underscore names (properties below):
        # a public Tensor attribute would enter nn.scan's per-layer config
        # signature as None before the first forward and vanish after it,
        # costing the homogeneity check a spurious retrace
        self._aux_loss: Optional[Tensor] = None
        self._z_loss: Optional[Tensor] = None
        self._router_stats: Optional[Tensor] = None
        self._moe_vec: Optional[Tensor] = None
        self._last_tokens = 0

    # last-forward outputs (same-trace values: read them in the same
    # trace/step that produced them)
    @property
    def aux_loss(self):
        """GShard load-balance loss of the last forward."""
        return self._aux_loss

    @aux_loss.setter
    def aux_loss(self, v):
        self.__dict__["_aux_loss"] = v

    @property
    def z_loss(self):
        """Router z-loss (squared logsumexp) of the last forward."""
        return self._z_loss

    @property
    def router_stats(self):
        """[drop_frac, entropy, balance_frac, load_0..E-1] f32 vector."""
        return self._router_stats

    @property
    def moe_vec(self):
        """[aux, z, drop, entropy, balance, load_0..E-1] f32 vector — the
        per-layer side output models thread through scan-over-layers."""
        return self._moe_vec

    def _capacity(self, tokens: int) -> int:
        return moe_capacity(tokens, self.capacity_factor, self.num_experts)

    # -- expert-parallel eligibility ---------------------------------------
    def _ep_degree(self) -> int:
        from ...distributed import env as dist_env
        mesh = dist_env.get_mesh()
        if mesh is not None and EP_AXIS in mesh.axis_names:
            return int(mesh.shape[EP_AXIS])
        return 1

    def _ep_eligible(self, n: int, tokens: int) -> bool:
        """Whether the explicit shard_map + all_to_all program can run
        (callers only ask when an ep>1 mesh is active); ineligibility is
        counted as a fallback."""
        from ...distributed import env as dist_env
        from ...distributed.meta_parallel.spmd_pipeline import (
            manual_collectives_ok)
        if not get_flag("moe_expert_parallel"):
            note_moe_fallback("flag_off")
            return False
        if not self._stacked:
            note_moe_fallback("heterogeneous_experts")
            return False
        if not self._default_gate:
            note_moe_fallback("custom_gate")
            return False
        if self.num_experts % n or tokens % n:
            note_moe_fallback(
                "indivisible", f"E={self.num_experts} T={tokens} ep={n}")
            return False
        mesh = dist_env.get_mesh()
        if not manual_collectives_ok(mesh, EP_AXIS):
            note_moe_fallback(
                "manual_collectives_unsupported",
                f"backend={jax.default_backend()} mesh="
                f"{dict(mesh.shape) if mesh is not None else None}")
            return False
        return True

    # -- forward -----------------------------------------------------------
    def forward(self, x):
        B, S, D = x.shape
        tokens = B * S
        flat = x.reshape((tokens, D))

        # probe the ep degree for hetero stacks too: _ep_eligible is what
        # records the counted heterogeneous_experts fallback on ep meshes
        n = self._ep_degree()
        if n > 1 and self._ep_eligible(n, tokens):
            out, aux, z, stats = self._expert_parallel_forward(
                flat, n, tokens, D)
        else:
            out, aux, z, stats = self._auto_forward(flat, tokens, D)

        self.__dict__["_aux_loss"] = aux
        self.__dict__["_z_loss"] = z
        self.__dict__["_router_stats"] = stats
        self.__dict__["_last_tokens"] = tokens
        self.__dict__["_moe_vec"] = apply(
            lambda a, zz, s: jnp.concatenate(
                [jnp.stack([a, zz]).astype(jnp.float32), s]),
            aux, z, stats, name="moe_vec")
        self._publish_stats()
        return out.reshape((B, S, D))

    # -- auto (GSPMD) path -------------------------------------------------
    def _router_logits(self, flat):
        """f32 router: the gate consumes an f32 view of the tokens with
        autocast disabled, so bf16 activation streams keep a full-
        precision router (near-tie argmaxes and the z-loss are
        ill-conditioned in half precision)."""
        from ...amp.auto_cast import auto_cast
        flat32 = apply(lambda a: a.astype(jnp.float32), flat,
                       name="moe_router_cast")
        with auto_cast(enable=False):
            return self.gate(flat32)

    def _auto_forward(self, flat, tokens: int, D: int):
        C = self._capacity(tokens)
        E, k = self.num_experts, self.top_k
        logits = self._router_logits(flat)

        routing = apply(lambda lg: tuple(topk_routing(lg, k, C)), logits,
                        name="moe_routing", _cache_token=("moe_routing",
                                                          E, C, k))
        gates, idx, pos, keep, aux, z, stats = routing
        for t in (idx, pos, keep, stats):
            t.stop_gradient = True        # integer-valued / telemetry

        mode = resolve_dispatch_mode(self.dispatch_mode)
        MOE_STATS[f"{mode}_dispatches"] += 1

        def _r(g, i, p, kp):
            return Routing(g, i, p, kp, None, None, None)

        if mode == "einsum":
            expert_in = apply(
                lambda ff, g, i, p, kp: einsum_dispatch(
                    ff, _r(g, i, p, kp), E, C),
                flat, gates, idx, pos, keep, name="moe_dispatch",
                _cache_token=("moe_dispatch_einsum", E, C, k))
        else:
            expert_in = apply(
                lambda ff, g, i, p, kp: sort_dispatch(
                    ff, _r(g, i, p, kp), E, C),
                flat, gates, idx, pos, keep, name="moe_dispatch",
                _cache_token=("moe_dispatch_sort", E, C, k))

        if self._stacked:
            expert_out = self.experts(expert_in)          # [E, C, D]
        else:
            outs = []
            for e, expert in enumerate(self.experts):
                outs.append(expert(expert_in[e]))         # [C, D]
            from ...tensor.manipulation import stack
            expert_out = stack(outs, axis=0)              # [E, C, D]

        if mode == "einsum":
            out = apply(
                lambda eo, g, i, p, kp: einsum_combine(
                    eo, _r(g, i, p, kp), C),
                expert_out, gates, idx, pos, keep, name="moe_combine",
                _cache_token=("moe_combine_einsum", E, C, k))
        else:
            out = apply(
                lambda eo, g, i, p, kp: sort_combine(
                    eo, _r(g, i, p, kp), C),
                expert_out, gates, idx, pos, keep, name="moe_combine",
                _cache_token=("moe_combine_sort", E, C, k))
        return out, aux, z, stats

    # -- explicit expert-parallel path -------------------------------------
    def _expert_parallel_forward(self, flat, n: int, tokens: int, D: int):
        """shard_map manual over ``ep``: each shard routes its T/n local
        tokens (LOCAL capacity discipline — the GShard per-device
        formulation; drop decisions are per shard), exchanges capacity
        chunks with all_to_all (double-buffered; see module docstring)
        and combines locally. Kept-token outputs match the auto path
        exactly; only drop decisions can differ when capacity overflows
        (local vs global cumsum order)."""
        from ...distributed import env as dist_env
        mode = resolve_dispatch_mode(self.dispatch_mode)
        chunks = resolve_a2a_chunks(self._capacity(tokens // n))
        mesh_prog = self._ep_program(n, tokens, D,
                                     str(flat._data.dtype)
                                     if isinstance(flat, Tensor)
                                     else str(flat.dtype))
        MOE_STATS["ep_dispatches"] += 1
        gate_leaves = [p for _, p in self.gate.named_parameters()]
        leaves = gate_leaves + [self.experts.w1, self.experts.b1,
                                self.experts.w2, self.experts.b2]

        def ep_fn(ff, *leaf_arrs):
            return _guarded_ep_dispatch(n, mesh_prog, ff, *leaf_arrs)

        out, aux, z, stats = apply(
            ep_fn, flat, *leaves, name="moe_expert_parallel",
            _cache_token=("moe_ep", id(self), n, tokens, D, mode, chunks,
                          self.capacity_factor, self.top_k,
                          id(dist_env.get_mesh())))
        stats.stop_gradient = True
        return out, aux, z, stats

    def _ep_program(self, n: int, tokens: int, D: int, dtype: str):
        """Build (and cache) the jitted shard_map expert-parallel program
        for (mesh, shapes, dispatch mode, chunking)."""
        from ...distributed import env as dist_env
        mesh = dist_env.get_mesh()
        mode = resolve_dispatch_mode(self.dispatch_mode)
        T_loc = tokens // n
        C_loc = self._capacity(T_loc)
        chunks = resolve_a2a_chunks(C_loc)
        cache = self.__dict__.setdefault("_ep_cache", {})
        ckey = (id(mesh), n, tokens, D, dtype, mode, chunks,
                self.capacity_factor, self.top_k)
        cached = cache.get(ckey)
        if cached is not None:
            return cached

        E, k, act = self.num_experts, self.top_k, self.experts.activation
        E_loc = E // n
        cs = C_loc // chunks
        prec = matmul_precision()
        n_gate = len([1 for _ in self.gate.named_parameters()])

        def body(x_loc, *leaves):
            gw = leaves[0]
            gb = leaves[1] if n_gate > 1 else None
            w1, b1, w2, b2 = leaves[n_gate:]
            logits = jnp.matmul(x_loc.astype(jnp.float32),
                                gw.astype(jnp.float32), precision=prec)
            if gb is not None:
                logits = logits + gb.astype(jnp.float32)
            r = topk_routing(logits, k, C_loc)
            if mode == "einsum":
                expert_in = einsum_dispatch(x_loc, r, E, C_loc)
            else:
                expert_in = sort_dispatch(x_loc, r, E, C_loc)

            # tokens-out exchanges for EVERY chunk issue before any
            # expert compute; each chunk's tokens-back exchange issues
            # right after its FFN — with chunks >= 2 the async scheduler
            # can hide chunk i+1's exchange behind chunk i's compute
            sent = []
            for c in range(chunks):
                piece = expert_in[:, c * cs:(c + 1) * cs]
                piece = piece.reshape(n, E_loc, cs, D)
                sent.append(jax.lax.all_to_all(
                    piece, EP_AXIS, split_axis=0, concat_axis=0,
                    tiled=False))                  # [n(src), E_loc, cs, D]
            back = []
            for c in range(chunks):
                rec = sent[c].transpose(1, 0, 2, 3).reshape(
                    E_loc, n * cs, D)
                y_c = expert_ffn_apply(rec, w1, b1, w2, b2, act)
                y_c = y_c.reshape(E_loc, n, cs, D).transpose(1, 0, 2, 3)
                back.append(jax.lax.all_to_all(
                    y_c, EP_AXIS, split_axis=0, concat_axis=0,
                    tiled=False))                  # [n(home), E_loc, cs, D]
            expert_out = jnp.concatenate(
                [b.reshape(E, cs, D) for b in back], axis=1)

            if mode == "einsum":
                y = einsum_combine(expert_out, r, C_loc)
            else:
                y = sort_combine(expert_out, r, C_loc)

            aux = jax.lax.pmean(r.aux, EP_AXIS)
            z = jax.lax.pmean(r.z, EP_AXIS)
            stats = jax.lax.pmean(r.stats, EP_AXIS)
            # balance recomputed from the MEAN load shares so the scalar
            # stays consistent with the loads the report renders
            load = stats[len(STATS_FIELDS):]
            stats = stats.at[2].set(
                1.0 - 0.5 * jnp.sum(jnp.abs(load - 1.0 / E)))
            return y, aux, z, stats

        gate_specs = (P(),) * n_gate
        prog = jax.jit(dist_env.shard_map(
            body, mesh=mesh,
            in_specs=(P(EP_AXIS),) + gate_specs + (P(EP_AXIS),) * 4,
            out_specs=(P(EP_AXIS), P(), P(), P()),
            axis_names={EP_AXIS}, check_vma=False))
        cache[ckey] = prog
        return prog

    # -- telemetry ---------------------------------------------------------
    def _publish_stats(self):
        """Always-on router telemetry (monitor-gated, like every hot-path
        registry stream): publishes when the stats are CONCRETE — eager
        forwards. Inside a jitted TrainStep the values are tracers; use
        :func:`publish_router_stats` after an eager forward to harvest."""
        stats = self.router_stats
        if stats is None or isinstance(stats._data, jax.core.Tracer):
            return
        from ...monitor import enabled as _mon_enabled
        if not _mon_enabled():
            return
        _publish_one(self, count_drops=True)


def _guarded_ep_dispatch(n: int, prog, *args):
    """Eager expert-parallel dispatches run under the PR 5 collective
    watchdog (FLAGS_collective_timeout_s + chaos ``collective.hang``) so
    a hung expert all_to_all raises CollectiveTimeoutError; traced calls
    (inside an outer jit) bypass — the enclosing TrainStep guards its own
    dispatch."""
    if any(isinstance(a, jax.core.Tracer)
           for a in jax.tree_util.tree_leaves(args)):
        return prog(*args)
    from ...distributed.collective import _run_collective
    return _run_collective("moe.all_to_all", moe_ep_group(n), prog, *args)


def _publish_row(stats_row, label: str, num_experts: int, registry=None,
                 dropped_assignments=None):
    """Publish one layer's router gauges from a raw stats row
    ``[drop_frac, entropy, balance_frac, load_0..E-1]`` (numpy/float
    values). Shared by MoELayer telemetry and GPTModel's scan-side-output
    harvest."""
    from ...monitor import get_registry
    reg = registry or get_registry()
    s = [float(v) for v in stats_row]
    nf = len(STATS_FIELDS)
    reg.gauge("moe_router_drop_pct",
              "dropped (token, choice) assignments, % of T*k"
              ).set(100.0 * s[0], layer=label)
    reg.gauge("moe_router_entropy",
              "mean per-token routing entropy (nats)"
              ).set(s[1], layer=label)
    reg.gauge("moe_router_balance_pct",
              "expert-load balance: 100 * (1 - TV distance from "
              "uniform); 100 = perfectly balanced").set(
                  100.0 * s[2], layer=label)
    for e, v in enumerate(s[nf:nf + num_experts]):
        reg.gauge("moe_expert_load_share",
                  "per-expert share of kept assignments").set(
                      v, layer=label, expert=e)
    if dropped_assignments is not None:
        reg.counter("moe_dropped_tokens_total",
                    "capacity-overflow-dropped (token, choice) "
                    "assignments").inc(round(dropped_assignments),
                                       layer=label)


def _publish_one(layer: MoELayer, registry=None, count_drops=False):
    import numpy as np
    s = np.asarray(layer.router_stats._data, dtype=np.float64)
    dropped = (float(s[0]) * layer._last_tokens * layer.top_k
               if count_drops else None)
    _publish_row(s, layer._label, layer.num_experts, registry,
                 dropped_assignments=dropped)


def publish_router_stats(model, registry=None) -> int:
    """Walk ``model`` for MoE layers with CONCRETE router stats (i.e.
    after an eager forward) and publish their ``moe_router_*`` gauges;
    returns the number of layers published. The bench and
    tools/monitor_report.py --moe consume the result."""
    count = 0
    layers = [model] if isinstance(model, MoELayer) else \
        [l for _, l in model.named_sublayers(include_self=True)
         if isinstance(l, MoELayer)]
    for l in layers:
        if l.router_stats is None or \
                isinstance(l.router_stats._data, jax.core.Tracer):
            continue
        _publish_one(l, registry)
        count += 1
    return count
