"""Synthetic open-loop load generator for the serving engine.

Open-loop means arrivals follow a FIXED schedule (Poisson process at
``rate_rps``) regardless of how fast the engine drains — the honest way
to measure serving latency: a closed-loop driver (next request only
after the previous completes) hides queueing delay exactly when the
system saturates. Prompt and generation lengths are drawn per request
from uniform ranges; everything is seeded, so a load run replays
exactly (the same property the chaos harness pins for faults).

``run_open_loop`` drives the engine inline: it submits every request
whose arrival time has passed, then runs one engine step, until the
schedule is exhausted and the engine drains. ``time_scale`` compresses
the schedule for tests (arrivals only — measured latencies are real).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from .sampling import SamplingParams
from .scheduler import Request

__all__ = ["LoadSpec", "build_requests", "run_open_loop"]


@dataclass
class LoadSpec:
    num_requests: int = 16
    rate_rps: float = 4.0
    prompt_len_range: Tuple[int, int] = (16, 64)
    max_new_range: Tuple[int, int] = (8, 32)
    vocab_size: int = 50304
    seed: int = 0
    sampling: Optional[SamplingParams] = None


def build_requests(spec: LoadSpec) -> List[Tuple[float, Request]]:
    """[(arrival_offset_s, Request), ...] sorted by arrival. Poisson
    arrivals (exponential gaps at ``rate_rps``), uniform prompt/output
    lengths, uniform random token ids — deterministic per seed."""
    rng = np.random.default_rng(spec.seed)
    gaps = rng.exponential(1.0 / max(spec.rate_rps, 1e-9),
                           spec.num_requests)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0                       # first request at t=0
    out = []
    lo_p, hi_p = spec.prompt_len_range
    lo_n, hi_n = spec.max_new_range
    for i in range(spec.num_requests):
        plen = int(rng.integers(lo_p, hi_p + 1))
        prompt = rng.integers(0, spec.vocab_size, (plen,)).astype(np.int32)
        out.append((float(arrivals[i]), Request(
            prompt,
            max_new_tokens=int(rng.integers(lo_n, hi_n + 1)),
            sampling=spec.sampling or SamplingParams())))
    return out


def run_open_loop(engine, spec: LoadSpec, time_scale: float = 1.0,
                  clock=time.perf_counter) -> dict:
    """Drive ``engine`` through the schedule; returns
    ``engine.metrics_summary()`` augmented with offered load."""
    schedule = build_requests(spec)
    t0 = clock()
    i = 0
    while i < len(schedule) or engine.scheduler.has_work:
        now = clock() - t0
        while i < len(schedule) and \
                schedule[i][0] * time_scale <= now:
            engine.submit(schedule[i][1])
            i += 1
        if engine.scheduler.has_work:
            engine.step()
        elif i < len(schedule):
            # idle gap before the next arrival: sleep the remainder
            wait = schedule[i][0] * time_scale - (clock() - t0)
            if wait > 0:
                time.sleep(min(wait, 0.05))
    summary = engine.metrics_summary()
    summary["offered_rate_rps"] = spec.rate_rps / max(time_scale, 1e-9)
    summary["num_requests"] = spec.num_requests
    return summary
