"""Synthetic open-loop load generator for the serving engine.

Open-loop means arrivals follow a FIXED schedule regardless of how fast
the engine drains — the honest way to measure serving latency: a
closed-loop driver (next request only after the previous completes)
hides queueing delay exactly when the system saturates. Everything is
seeded, so a load run replays exactly (the same property the chaos
harness pins for faults).

Arrival processes (``LoadSpec.arrival``):

- ``poisson`` — exponential inter-arrival gaps at ``rate_rps`` (the
  classic memoryless open-loop load);
- ``gamma`` — Gamma-distributed gaps with the SAME mean rate but
  squared coefficient of variation ``burstiness`` (the shape parameter
  is ``1/burstiness``: > 1 clumps arrivals into bursts, < 1 produces
  smoother-than-poisson pacing);
- ``mmpp`` — a 2-state Markov-modulated Poisson process: a hidden state
  flips between a hot rate ``rate*(1+burstiness)`` and a cold rate
  ``rate/(1+burstiness)`` with probability ``mmpp_switch`` per arrival,
  gaps rescaled so the mean rate is still ``rate_rps`` — sustained
  overload episodes followed by idle valleys, the arrival shape that
  actually exercises shedding and the overload detector.

Per-request ``deadline_range`` / ``priority_choices`` sampling makes the
expiry and priority-lane paths reachable from ``bench.py --serve``. The
extra draws only happen when the corresponding field is set, so default
specs generate byte-identical traffic to the pre-resilience generator.

:class:`TokenBucket` is client-side rate limiting for loadgen-driven
tests: ``run_open_loop(..., token_bucket=...)`` drops (counts) arrivals
that exceed the bucket instead of submitting them. Server-side shedding
(:class:`~.resilience.ServerOverloaded`) is likewise counted, not
crashed on — an overloaded server answering "no" is the behaviour under
test, not an error in the driver.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from .resilience import DecodeWatchdogError, ServerOverloaded
from .sampling import SamplingParams
from .scheduler import Request

__all__ = ["LoadSpec", "TokenBucket", "build_requests",
           "run_fleet_open_loop", "run_open_loop"]

_ARRIVALS = ("poisson", "gamma", "mmpp")

#: run_open_loop gives up (re-raises) after this many watchdog trips in
#: a row with no successful step between them: a backend that hangs on
#: EVERY retry is down, not slow
MAX_CONSECUTIVE_WATCHDOG_TRIPS = 8


@dataclass
class LoadSpec:
    num_requests: int = 16
    rate_rps: float = 4.0
    prompt_len_range: Tuple[int, int] = (16, 64)
    max_new_range: Tuple[int, int] = (8, 32)
    vocab_size: int = 50304
    seed: int = 0
    sampling: Optional[SamplingParams] = None
    #: arrival process: poisson | gamma | mmpp (see module docstring)
    arrival: str = "poisson"
    #: gamma: squared CV of the gaps; mmpp: hot/cold rate swing. 1.0
    #: with gamma degenerates to poisson.
    burstiness: float = 1.0
    #: mmpp: per-arrival probability of flipping the hidden rate state
    mmpp_switch: float = 0.1
    #: uniform per-request deadline_s sample; None = no deadlines
    deadline_range: Optional[Tuple[float, float]] = None
    #: uniform per-request priority sample; None = all priority 0
    priority_choices: Optional[Tuple[int, ...]] = None
    #: chat-style shared prefixes (ISSUE 15): > 0 = every prompt opens
    #: with one of ``prefix_pool_size`` fixed prefixes of this many
    #: tokens (a "system prompt"), drawn with bounded-zipf reuse so a
    #: hot head of prefixes dominates — the traffic shape the radix
    #: prefix cache exists for (BENCH_serve measures hit rate on it).
    #: 0 (default) = no prefixes, byte-identical to pre-ISSUE-15 specs.
    shared_prefix_len: int = 0
    #: number of distinct prefixes in the pool
    prefix_pool_size: int = 8
    #: zipf exponent of prefix reuse (rank==index; higher = hotter head)
    prefix_zipf: float = 1.1
    #: fleet workload (ISSUE 16): > 0 = every request belongs to one of
    #: this many tenants, drawn zipf(``prefix_zipf``) per request, and
    #: each tenant owns its OWN prefix pool (``prefix_pool_size``
    #: prefixes of ``shared_prefix_len`` tokens, per-tenant seeded) —
    #: the traffic shape prefix-affine routing exists for: a tenant's
    #: whole prefix family hashes to one replica, so its radix tree
    #: stays hot there. Requires ``shared_prefix_len > 0``. 0 (default)
    #: = the single shared pool above, byte-identical to pre-fleet
    #: specs.
    tenants: int = 0
    #: multi-tenant LoRA traffic (ISSUE 17): > 0 = every tenanted
    #: request names one of this many per-tenant adapters
    #: ("tenant{t}/adapter{k}", k uniform from a fixed-seed SIDE
    #: generator, so arming adapters perturbs none of the default
    #: draws — arrivals/prompts/lengths replay exactly) and carries its
    #: tenant name, reaching the per-tenant quota + batched-bgmv paths
    #: from ``bench.py --serve``. Requires ``tenants > 0``. 0 (default)
    #: = no adapter/tenant stamping, byte-identical to pre-LoRA specs.
    adapter_pool: int = 0
    #: model-lifecycle traffic tagging (ISSUE 20): > 0 = stamp each
    #: request with the A/B arm (``lifecycle_arm``) a router running
    #: ``TrafficSplit(ab_frac=ab_split, seed=split_seed)`` would place
    #: it in — the SAME ``lifecycle.assign_arm`` hash of the request
    #: id, no RNG draws, so arming it perturbs nothing about the
    #: default draws (arrivals/prompts/lengths replay exactly; pinned).
    #: 0.0 (default) = no stamping, byte-identical to pre-lifecycle
    #: specs.
    ab_split: float = 0.0
    #: > 0 = stamp ``lifecycle_shadow=True`` on the requests a
    #: ``TrafficSplit(shadow_frac=...)`` router would mirror (same
    #: deterministic ``lifecycle.should_shadow`` hash); 0.0 (default)
    #: = no stamping
    shadow_frac: float = 0.0
    #: seed the tags hash with (matches ``TrafficSplit.seed``)
    split_seed: int = 0


class TokenBucket:
    """Deterministic client-side rate limiter: ``rate`` tokens/s refill
    up to a ``burst`` cap; :meth:`admit` spends one token or answers
    False. Driven by the caller's clock values, so tests replay
    exactly."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst < 1:
            raise ValueError("token bucket needs rate > 0 and burst >= 1")
        self.rate = float(rate)
        self.capacity = float(burst)
        self.tokens = float(burst)
        self._last: Optional[float] = None

    def admit(self, now: float) -> bool:
        if self._last is not None:
            self.tokens = min(self.capacity,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


def _arrival_gaps(spec: LoadSpec, rng) -> np.ndarray:
    """Inter-arrival gaps (seconds) for ``num_requests`` arrivals, mean
    rate ``rate_rps`` for every mode."""
    if spec.arrival not in _ARRIVALS:
        raise ValueError(f"unknown arrival mode {spec.arrival!r}; one "
                         f"of {_ARRIVALS}")
    mean = 1.0 / max(spec.rate_rps, 1e-9)
    n = spec.num_requests
    if spec.arrival == "gamma" and spec.burstiness <= 0.0:
        raise ValueError("gamma arrival needs burstiness > 0 "
                         "(= the squared CV of the gaps)")
    if spec.arrival == "poisson" or \
            (spec.arrival == "gamma" and spec.burstiness == 1.0):
        return rng.exponential(mean, n)
    if spec.arrival == "gamma":
        # CV^2 = burstiness: > 1 clumps arrivals, < 1 smooths them
        # (shape > 1, more regular than poisson) — both valid loads
        shape = 1.0 / float(spec.burstiness)
        return rng.gamma(shape, mean / shape, n)
    # mmpp: hidden 2-state rate, switched per arrival
    swing = 1.0 + max(float(spec.burstiness), 0.0)
    rates = (spec.rate_rps * swing, spec.rate_rps / swing)
    state = 0
    gaps = np.empty((n,), np.float64)
    for i in range(n):
        gaps[i] = rng.exponential(1.0 / max(rates[state], 1e-9))
        if rng.random() < spec.mmpp_switch:
            state = 1 - state
    # symmetric switching -> stationary occupancy 1/2 per state, so the
    # raw expected gap is (1/swing + swing)/(2*rate); rescale to keep
    # the promised mean rate exactly (offered_rate_rps stays honest)
    gaps *= 2.0 / (swing + 1.0 / swing)
    return gaps


def build_requests(spec: LoadSpec) -> List[Tuple[float, Request]]:
    """[(arrival_offset_s, Request), ...] sorted by arrival — the chosen
    arrival process, uniform prompt/output lengths, uniform random token
    ids, optional deadline/priority sampling — deterministic per seed."""
    if spec.adapter_pool > 0 and spec.tenants <= 0:
        raise ValueError("adapter_pool needs tenants > 0 (adapters are "
                         "per-tenant)")
    rng = np.random.default_rng(spec.seed)
    # adapter draws come from their own fixed-seed generator so arming
    # adapter_pool leaves every draw from ``rng`` untouched (pinned)
    arng = (np.random.default_rng(spec.seed ^ 0xADA9)
            if spec.adapter_pool > 0 else None)
    arrivals = np.cumsum(_arrival_gaps(spec, rng))
    arrivals[0] = 0.0                       # first request at t=0
    out = []
    lo_p, hi_p = spec.prompt_len_range
    lo_n, hi_n = spec.max_new_range
    prefixes = prefix_cdf = None
    tenant_pools = tenant_cdf = None

    def _zipf_cdf(n: int) -> np.ndarray:
        w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64),
                           float(spec.prefix_zipf))
        return np.cumsum(w / w.sum())

    if spec.shared_prefix_len > 0 and spec.tenants > 0:
        # per-tenant prefix pools (ISSUE 16): tenant t's pool comes from
        # its own fixed-seed side generator, so pools are disjoint and
        # stable per seed, and — like the single-pool path — none of
        # the default draws below are perturbed by building them
        tenant_pools = []
        for t in range(spec.tenants):
            prng = np.random.default_rng(
                spec.seed ^ 0x5A5A ^ (0x1000 * (t + 1)))
            tenant_pools.append(prng.integers(
                0, spec.vocab_size,
                (max(1, spec.prefix_pool_size), spec.shared_prefix_len)
            ).astype(np.int32))
        tenant_cdf = _zipf_cdf(spec.tenants)
        prefix_cdf = _zipf_cdf(tenant_pools[0].shape[0])
    elif spec.shared_prefix_len > 0:
        # the prefix pool and its zipf CDF draw from a fixed-seed side
        # generator, so enabling prefixes perturbs NOTHING about the
        # default draws below (arrivals/lengths/tails replay exactly)
        prng = np.random.default_rng(spec.seed ^ 0x5A5A)
        prefixes = prng.integers(
            0, spec.vocab_size,
            (max(1, spec.prefix_pool_size), spec.shared_prefix_len)
        ).astype(np.int32)
        prefix_cdf = _zipf_cdf(prefixes.shape[0])
    for i in range(spec.num_requests):
        plen = int(rng.integers(lo_p, hi_p + 1))
        prompt = rng.integers(0, spec.vocab_size, (plen,)).astype(np.int32)
        tenant = adapter = None
        if tenant_pools is not None:
            t = int(np.searchsorted(tenant_cdf, rng.random()))
            t = min(t, len(tenant_pools) - 1)
            pool = tenant_pools[t]
            pi = int(np.searchsorted(prefix_cdf, rng.random()))
            prompt = np.concatenate([pool[min(pi, len(pool) - 1)],
                                     prompt])
            if arng is not None:
                tenant = f"tenant{t}"
                adapter = (f"tenant{t}/adapter"
                           f"{int(arng.integers(0, spec.adapter_pool))}")
        elif prefixes is not None:
            pi = int(np.searchsorted(prefix_cdf, rng.random()))
            prompt = np.concatenate([prefixes[min(pi, len(prefix_cdf)
                                                  - 1)], prompt])
        deadline = None
        if spec.deadline_range is not None:
            lo_d, hi_d = spec.deadline_range
            deadline = float(rng.uniform(lo_d, hi_d))
        priority = 0
        if spec.priority_choices:
            priority = int(spec.priority_choices[
                int(rng.integers(0, len(spec.priority_choices)))])
        req = Request(
            prompt,
            max_new_tokens=int(rng.integers(lo_n, hi_n + 1)),
            sampling=spec.sampling or SamplingParams(),
            deadline_s=deadline, priority=priority,
            tenant=tenant, adapter=adapter)
        if spec.ab_split > 0.0 or spec.shadow_frac > 0.0:
            # pure request-id hashes (lifecycle.assign_arm /
            # should_shadow) — zero draws from ``rng``, so the tags
            # ride along without perturbing any default field (pinned)
            from .lifecycle import assign_arm, should_shadow
            req.lifecycle_arm = assign_arm(
                int(req.request_id), spec.split_seed, spec.ab_split)
            req.lifecycle_shadow = should_shadow(
                int(req.request_id), spec.split_seed, spec.shadow_frac)
        out.append((float(arrivals[i]), req))
    return out


def run_open_loop(engine, spec: LoadSpec, time_scale: float = 1.0,
                  clock=time.perf_counter,
                  token_bucket: Optional[TokenBucket] = None) -> dict:
    """Drive ``engine`` through the schedule; returns
    ``engine.metrics_summary()`` augmented with offered load and the
    client-visible refusal counts. Server-side shedding
    (:class:`ServerOverloaded`) and watchdog trips
    (:class:`DecodeWatchdogError`) are COUNTED and survived — overload
    behaviour is what this driver exists to measure."""
    schedule = build_requests(spec)
    t0 = clock()
    i = 0
    rejected = throttled = watchdog_trips = 0
    consecutive_trips = 0
    while i < len(schedule) or engine.scheduler.has_work:
        now = clock() - t0
        while i < len(schedule) and \
                schedule[i][0] * time_scale <= now:
            if token_bucket is not None and \
                    not token_bucket.admit(now):
                throttled += 1
            else:
                try:
                    engine.submit(schedule[i][1])
                except ServerOverloaded:
                    rejected += 1
            i += 1
        if engine.scheduler.has_work:
            try:
                engine.step()
                consecutive_trips = 0
            except DecodeWatchdogError as e:
                # hung dispatch converted to a structured error: count
                # it and retry the step (token-exact for greedy) — but
                # a PERSISTENTLY hung backend must not become an
                # infinite retry loop that piles up abandoned threads,
                # and a trip that lost donated pools cannot retry at all
                watchdog_trips += 1
                consecutive_trips += 1
                if not e.retry_safe \
                        or consecutive_trips >= MAX_CONSECUTIVE_WATCHDOG_TRIPS:
                    raise
        elif i < len(schedule):
            # idle gap before the next arrival: sleep the remainder
            wait = schedule[i][0] * time_scale - (clock() - t0)
            if wait > 0:
                time.sleep(min(wait, 0.05))
    summary = engine.metrics_summary()
    summary["offered_rate_rps"] = spec.rate_rps / max(time_scale, 1e-9)
    summary["num_requests"] = spec.num_requests
    summary["requests_rejected"] = rejected
    summary["requests_throttled"] = throttled
    summary["watchdog_trips"] = watchdog_trips
    return summary


def run_fleet_open_loop(router, spec: LoadSpec,
                        time_scale: float = 1.0,
                        clock=time.perf_counter) -> dict:
    """Drive a :class:`~.router.FleetRouter` through the same open-loop
    arrival contract as :func:`run_open_loop`: the SAME seeded schedule
    (so a fleet run and a single-engine run see identical traffic), the
    router places each arrival, and every live replica is stepped
    round-robin between arrivals. Router-level refusals (no ready
    replica / all replicas shed) are counted, not crashed on. Returns
    ``router.summary()`` augmented with the offered load."""
    schedule = build_requests(spec)
    t0 = clock()
    i = 0
    rejected = 0
    while i < len(schedule) or any(
            r.alive and r.engine.scheduler.has_work
            for r in router.replicas.values()):
        now = clock() - t0
        while i < len(schedule) and \
                schedule[i][0] * time_scale <= now:
            try:
                router.submit(schedule[i][1])
            except ServerOverloaded:
                rejected += 1
            i += 1
        if not router.step_all() and i < len(schedule):
            wait = schedule[i][0] * time_scale - (clock() - t0)
            if wait > 0:
                time.sleep(min(wait, 0.05))
    summary = router.summary()
    summary["offered_rate_rps"] = spec.rate_rps / max(time_scale, 1e-9)
    summary["num_requests"] = spec.num_requests
    summary["requests_rejected_router"] = rejected
    return summary
