"""Zero-downtime model lifecycle: shadow/A-B validation and the
SLO-guarded promotion controller (ISSUE 20).

A weight push on a live fleet runs a state machine::

    serving -> staging -> baking -> promoted
                   \\          \\-> rolled-back
                    \\-> serving (aborted: refused push / dead replica)

``staging`` hot-swaps the candidate manifest onto ONE replica
(:meth:`ServingEngine.swap_weights` — torn/corrupt pushes refuse there
and the push aborts with the baseline untouched). ``baking`` splits
traffic via :class:`TrafficSplit`: a deterministic hash of the request
id routes an A/B fraction of live traffic to the candidate and/or
mirrors a shadow fraction (responses discarded, fully measured). The
:class:`LifecycleController` feeds every candidate-arm outcome into an
:class:`~paddle_tpu.monitor.slo.SLOTracker` and, over the bake window,
either promotes (rolling swap of the remaining replicas, one at a time
— never two down at once) or auto-rolls-back to the previous manifest,
writing an incident bundle and flight events with the decision inputs.

Both the router and the load generator tag requests through the SAME
seeded hash helpers (:func:`assign_arm` / :func:`should_shadow`), so an
offline replay of a traffic log lands every request in the same arm the
fleet served it from.

Everything here is flag-gated (``FLAGS_serve_lifecycle`` for the
controller, ``FLAGS_serve_traffic_split`` for the router split,
``FLAGS_serve_hot_swap`` for the engine swap); flags off, none of this
constructs and the serving path is byte-identical to the pre-lifecycle
engine.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..monitor import get_registry
from ..monitor import flight_recorder as _flight
from ..monitor.flight_recorder import safe_record_event
from ..monitor.slo import SLOTracker
from ..testing import chaos
from .engine import WeightSwapError

__all__ = ["TrafficSplit", "LifecycleConfig", "LifecycleController",
           "assign_arm", "should_shadow"]

#: lifecycle states, in gauge-code order (serve_lifecycle_state)
STATES = ("serving", "staging", "baking", "promoted", "rolled-back")

ARMS = ("baseline", "candidate", "shadow")


def _u01(salt: str, seed: int, request_id: int) -> float:
    """Uniform [0, 1) draw that is a pure function of (salt, seed,
    request id) — no RNG state, so the router, the load generator and
    an offline replay all agree on every request's assignment."""
    h = hashlib.blake2b(f"{salt}:{seed}:{request_id}".encode(),
                       digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


def assign_arm(request_id: int, seed: int, candidate_frac: float) -> str:
    """Deterministic A/B split: ``"candidate"`` for ``candidate_frac``
    of request ids, ``"baseline"`` for the rest. Distinct salt from
    :func:`should_shadow` so the two decisions are independent."""
    if candidate_frac <= 0.0:
        return "baseline"
    return ("candidate"
            if _u01("ab", seed, request_id) < candidate_frac
            else "baseline")


def should_shadow(request_id: int, seed: int, shadow_frac: float) -> bool:
    """Deterministic shadow sampling: True for ``shadow_frac`` of
    request ids (the request is ALSO mirrored to the candidate)."""
    if shadow_frac <= 0.0:
        return False
    return _u01("shadow", seed, request_id) < shadow_frac


@dataclass(frozen=True)
class TrafficSplit:
    """Router traffic-split policy for one candidate bake.

    ``ab_frac`` of live traffic routes TO the candidate replica (its
    responses are served to clients — the A/B arm); ``shadow_frac`` of
    baseline traffic is ALSO mirrored to the candidate with the mirror's
    response discarded but fully measured. Both draws hash the request
    id with ``seed`` (see :func:`assign_arm` / :func:`should_shadow`),
    so assignment is deterministic and replayable."""

    candidate: str
    ab_frac: float = 0.0
    shadow_frac: float = 0.0
    seed: int = 0

    def __post_init__(self):
        for name in ("ab_frac", "shadow_frac"):
            v = getattr(self, name)
            if not (0.0 <= v <= 1.0):
                raise ValueError(f"TrafficSplit.{name}={v} outside [0, 1]")


@dataclass
class LifecycleConfig:
    """Promotion-controller policy knobs.

    The candidate bakes for ``bake_window_s``; during the bake ANY of
    the rollback triggers fires immediately (failure count over
    ``max_nonfinite``, availability burn over ``max_burn`` once
    ``min_requests`` candidate-arm outcomes exist, candidate p99 over
    ``max_p99_ratio`` x baseline p99). Surviving the window with at
    least ``min_requests`` outcomes promotes."""

    bake_window_s: float = 5.0
    min_requests: int = 10
    #: availability burn-rate threshold on the candidate arm (1.0 =
    #: exactly consuming budget; SRE fast-burn pages at >= 2)
    max_burn: float = 2.0
    burn_window_s: float = 5.0
    #: candidate-arm availability objective the burn is measured against
    objective: float = 0.999
    #: candidate-arm failures tolerated before instant rollback (the
    #: engine turns non-finite logits into per-request failures, so a
    #: NaN push shows up here first)
    max_nonfinite: int = 0
    #: 0 disables the latency trigger
    max_p99_ratio: float = 0.0
    #: where rollback incident bundles land (None = no bundles)
    incident_dir: Optional[str] = None


class _ArmStats:
    __slots__ = ("outcomes", "e2e")

    def __init__(self):
        self.outcomes: Dict[str, int] = {}
        self.e2e: List[float] = []

    def observe(self, outcome: str, e2e_s: Optional[float]) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if e2e_s is not None:
            self.e2e.append(float(e2e_s))

    @property
    def total(self) -> int:
        return sum(self.outcomes.values())

    def p99(self) -> Optional[float]:
        if not self.e2e:
            return None
        xs = sorted(self.e2e)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def snapshot(self) -> dict:
        return {"outcomes": dict(self.outcomes), "total": self.total,
                "e2e_p99_s": self.p99()}


class LifecycleController:
    """Drives one weight push through the lifecycle state machine on a
    :class:`~paddle_tpu.serving.router.FleetRouter` fleet.

    The router reports every terminal split-arm outcome via
    :meth:`observe` (from its sweep) and ticks :meth:`maybe_decide`
    after each scheduling pass; tests and operators can also call
    :meth:`maybe_decide` directly. Constructing the controller requires
    ``FLAGS_serve_lifecycle`` (read once here) — flags off, no
    controller exists and the router never consults one."""

    def __init__(self, router, config: Optional[LifecycleConfig] = None,
                 clock=time.perf_counter):
        from ..core.flags import get_flag
        if not bool(get_flag("serve_lifecycle")):
            raise RuntimeError(
                "FLAGS_serve_lifecycle is off — the promotion "
                "controller is disarmed (the flag is read once at "
                "construction)")
        self.router = router
        self.config = config or LifecycleConfig()
        self.clock = clock
        self.state = "serving"
        self._manifest: Optional[str] = None
        self._candidate: Optional[str] = None
        self._split: Optional[TrafficSplit] = None
        self._bake_start: Optional[float] = None
        self._arms: Dict[str, _ArmStats] = {a: _ArmStats() for a in ARMS}
        self._slo: Optional[SLOTracker] = None
        self._decision: Optional[dict] = None
        self._incidents = 0
        #: transition log for monitor_report --lifecycle
        self.timeline: List[dict] = []
        self._transition("serving", self.clock(), detail="attached")
        router.attach_lifecycle(self)

    # -- state machine -------------------------------------------------------
    def _transition(self, to: str, t: float, **detail) -> None:
        entry = {"t": t, "from": self.state, "to": to,
                 "epoch": self._engine_epoch(), **detail}
        self.state = to
        self.timeline.append(entry)
        reg = get_registry()
        reg.gauge(
            "serve_lifecycle_state",
            "promotion controller state (0 serving, 1 staging, 2 "
            "baking, 3 promoted, 4 rolled-back)").set(
                float(STATES.index(to)))
        reg.counter(
            "serve_lifecycle_transitions_total",
            "promotion controller state transitions").inc(to=to)
        safe_record_event("lifecycle_transition", **{
            k: v for k, v in entry.items() if k != "t"})

    def _engine_epoch(self) -> Optional[int]:
        rep = (self.router.replica(self._candidate)
               if self._candidate else None)
        return rep.engine._weights_epoch if rep is not None else None

    def begin(self, manifest_dir: str, candidate: str,
              split: Optional[TrafficSplit] = None) -> dict:
        """Stage ``manifest_dir`` onto the ``candidate`` replica and
        start the bake. A refused push (torn manifest, tree mismatch)
        or a candidate that dies mid-staging ABORTS back to ``serving``
        with the baseline untouched; otherwise the router's traffic
        split arms and the state moves to ``baking``."""
        if self.state not in ("serving", "promoted", "rolled-back"):
            raise RuntimeError(
                f"lifecycle: begin() while {self.state!r} — one push "
                "at a time")
        rep = self.router.replica(candidate)
        if rep is None or not rep.alive:
            raise ValueError(f"lifecycle: no live replica {candidate!r}")
        t = self.clock()
        self._manifest = manifest_dir
        self._candidate = candidate
        self._decision = None
        self._arms = {a: _ArmStats() for a in ARMS}
        self._transition("staging", t, manifest=manifest_dir,
                         candidate=candidate)
        try:
            rep.engine.swap_weights(manifest_dir)
        except WeightSwapError as e:
            self._transition("serving", self.clock(), aborted="refused",
                             reason=e.reason)
            safe_record_event("lifecycle_abort", reason=e.reason,
                              manifest=manifest_dir)
            return {"state": self.state, "aborted": "refused",
                    "reason": e.reason}
        if chaos.active() and chaos.probe("serve.swap.replica_die_mid_swap"):
            # the candidate died with the swap staged: migrate its
            # in-flight work (router journal resubmit) and abort — the
            # baseline arm never saw the push
            self.router.kill_replica(candidate)
            self._transition("serving", self.clock(),
                             aborted="replica_died", candidate=candidate)
            safe_record_event("lifecycle_abort", reason="replica_died",
                              candidate=candidate,
                              manifest=manifest_dir)
            return {"state": self.state, "aborted": "replica_died"}
        cfg = self.config
        self._slo = SLOTracker(
            "lifecycle_candidate", cfg.objective,
            windows=(cfg.burn_window_s,), clock=self.clock)
        self._split = split or TrafficSplit(candidate=candidate,
                                            shadow_frac=1.0)
        self.router.set_traffic_split(self._split)
        self._bake_start = self.clock()
        self._transition("baking", self._bake_start,
                         ab_frac=self._split.ab_frac,
                         shadow_frac=self._split.shadow_frac)
        return {"state": self.state, "epoch": self._engine_epoch()}

    # -- observation (fed by the router sweep) -------------------------------
    def observe(self, arm: str, outcome: str,
                e2e_s: Optional[float] = None,
                t: Optional[float] = None) -> None:
        """One terminal split-arm outcome. Candidate AND shadow
        outcomes feed the candidate SLO tracker — a shadow mirror runs
        the same candidate weights, its failures are the same signal."""
        if arm not in self._arms:
            return
        self._arms[arm].observe(outcome, e2e_s)
        if self._slo is not None and arm in ("candidate", "shadow"):
            t = self.clock() if t is None else t
            if outcome == "completed":
                self._slo.record(good=1, t=t)
            elif outcome in ("failed", "expired", "shed"):
                self._slo.record(bad=1, t=t)

    def _candidate_total(self) -> int:
        return (self._arms["candidate"].total
                + self._arms["shadow"].total)

    def _candidate_failures(self) -> int:
        return (self._arms["candidate"].outcomes.get("failed", 0)
                + self._arms["shadow"].outcomes.get("failed", 0))

    def maybe_decide(self, t: Optional[float] = None) -> Optional[str]:
        """Tick the bake: instant rollback on a tripped trigger,
        promotion once the window elapses with enough samples and no
        trigger. Returns the decision (``"promoted"``/``"rolled-back"``)
        the tick it happens, else None."""
        if self.state != "baking":
            return None
        t = self.clock() if t is None else t
        cfg = self.config
        burn = self._slo.burn_rate(cfg.burn_window_s, t=t) \
            if self._slo is not None else 0.0
        failures = self._candidate_failures()
        total = self._candidate_total()
        if failures > cfg.max_nonfinite:
            return self._rollback(t, "nonfinite", burn=burn,
                                  failures=failures)
        if total >= cfg.min_requests and burn > cfg.max_burn:
            return self._rollback(t, "slo_burn", burn=burn,
                                  failures=failures)
        if cfg.max_p99_ratio > 0.0 and total >= cfg.min_requests:
            cp = self._arms["candidate"].p99() \
                or self._arms["shadow"].p99()
            bp = self._arms["baseline"].p99()
            if cp is not None and bp and cp > cfg.max_p99_ratio * bp:
                return self._rollback(t, "latency", burn=burn,
                                      p99_ratio=cp / bp)
        if t - self._bake_start >= cfg.bake_window_s \
                and total >= cfg.min_requests:
            return self._promote(t, burn=burn)
        return None

    # -- decisions -----------------------------------------------------------
    def _decision_record(self, decision: str, t: float,
                         **detail) -> dict:
        d = {"decision": decision, "t": t,
             "manifest": self._manifest,
             "candidate": self._candidate,
             "bake_s": (t - self._bake_start
                        if self._bake_start is not None else None),
             "arms": {a: s.snapshot() for a, s in self._arms.items()},
             **detail}
        self._decision = d
        return d

    def _promote(self, t: float, **detail) -> str:
        """Roll the candidate manifest across the rest of the fleet,
        one replica at a time — a staged hot-swap never takes a replica
        out of service, and sequencing guarantees never-two-down even
        on drain-fallback swaps."""
        self.router.clear_traffic_split()
        self._split = None
        rolled = []
        cand = self.router.replica(self._candidate)
        if cand is not None and cand.alive:
            cand.engine.commit_swap()
        for rep in self.router.replicas.values():
            if rep.name == self._candidate or not rep.alive:
                continue
            info = rep.engine.swap_weights(self._manifest)
            if not info.get("pending"):
                # already cut over (idle / drain fallback): the anchor
                # tree can drop now; a still-pending swap keeps its
                # rollback anchor until the operator commits it
                rep.engine.commit_swap()
            rolled.append(rep.name)
            safe_record_event("lifecycle_replica_promoted",
                              replica=rep.name, manifest=self._manifest)
        rec = self._decision_record("promoted", t, rolled=rolled,
                                    **detail)
        self._transition("promoted", t, rolled=len(rolled), **detail)
        safe_record_event("lifecycle_promoted", manifest=self._manifest,
                          rolled=len(rolled), **detail)
        return rec["decision"]

    def _rollback(self, t: float, trigger: str, **detail) -> str:
        """Auto-rollback: tear the split down FIRST (no more traffic
        reaches the bad weights), restore the previous tree on the
        candidate, drop the bad tree, and leave the forensics — flight
        events and an incident bundle with the decision inputs."""
        self.router.clear_traffic_split()
        self._split = None
        cand = self.router.replica(self._candidate)
        if cand is not None and cand.alive:
            info = cand.engine.rollback_weights()
            if not info.get("pending"):
                cand.engine.commit_swap()     # drop the bad tree
        rec = self._decision_record("rolled-back", t, trigger=trigger,
                                    **detail)
        bundle = self._write_incident(trigger, rec)
        rec["incident"] = bundle
        self._transition("rolled-back", t, trigger=trigger, **detail)
        safe_record_event("lifecycle_rollback", trigger=trigger,
                          manifest=self._manifest, bundle=bundle,
                          **detail)
        return rec["decision"]

    def _write_incident(self, trigger: str, record: dict) -> Optional[str]:
        d = self.config.incident_dir
        if not d:
            return None
        base = os.path.join(d, f"lifecycle-{self._incidents:04d}-{trigger}")
        os.makedirs(base, exist_ok=True)
        self._incidents += 1
        with open(os.path.join(base, "incident.json"), "w") as f:
            json.dump(record, f, indent=2, sort_keys=True, default=str)
        if _flight.enabled():
            doc = _flight.get_flight_recorder().doc(
                reason=f"lifecycle_{trigger}")
            with open(os.path.join(base, "flight.json"), "w") as f:
                json.dump(doc, f, indent=2, default=str)
        return base

    # -- introspection -------------------------------------------------------
    def summary(self) -> dict:
        return {
            "state": self.state,
            "manifest": self._manifest,
            "candidate": self._candidate,
            "arms": {a: s.snapshot() for a, s in self._arms.items()},
            "burn": (self._slo.burn_rate(self.config.burn_window_s)
                     if self._slo is not None else None),
            "decision": self._decision,
            "timeline": list(self.timeline),
        }
