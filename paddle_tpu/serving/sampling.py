"""Batched next-token sampling with per-slot parameters.

One decode program serves every request in the batch even when requests
mix greedy / temperature / top-k / top-p settings: the parameters are
``[B]`` device arrays (arguments of the compiled step), and the math is
fully vectorized — never a per-request branch, never a recompile when a
slot's sampling config changes.

Conventions (matching ``models/generation.py``'s single-request
``_sample``): ``temperature <= 0`` means greedy (argmax); ``top_k <= 0``
disables the top-k filter; ``top_p >= 1`` disables nucleus filtering.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "filtered_logits", "sample_tokens"]

_NEG = -1e30


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode strategy. Defaults to greedy."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")


def filtered_logits(logits, temperature, top_k, top_p):
    """The temperature-scaled, top-k/top-p-filtered ``[B, V]`` logits
    that :func:`sample_tokens` draws from, with filtered-away entries at
    ``-1e30``. Exposed separately because speculative *stochastic*
    verification (ISSUE 16) needs the full per-row distribution — the
    accept probability of a drafted token is its softmax mass here, and
    the residual redraw samples from the same rows with the draft masked
    out — not just one sample."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    lg = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: keep values >= the k-th largest; k<=0 means keep all
    srt = jnp.sort(lg, axis=-1)[:, ::-1]                      # descending
    k_eff = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)
    kth = jnp.take_along_axis(srt, (k_eff - 1).astype(jnp.int32)[:, None],
                              axis=-1)
    lg = jnp.where(lg < kth, _NEG, lg)
    # top-p over the k-filtered distribution: keep the smallest prefix of
    # the sorted probs with cumulative mass >= top_p
    srt2 = jnp.sort(lg, axis=-1)[:, ::-1]
    cum = jnp.cumsum(jax.nn.softmax(srt2, axis=-1), axis=-1)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(
        srt2, jnp.clip(cutoff_idx, 0, V - 1)[:, None], axis=-1)
    return jnp.where(lg < cutoff, _NEG, lg)


def sample_tokens(logits, key, temperature, top_k, top_p):
    """Next token per row from ``[B, V]`` logits.

    ``temperature``/``top_p`` are ``[B]`` f32, ``top_k`` ``[B]`` int32.
    Rows with ``temperature <= 0`` take the argmax (their filtered-
    sampling lane still computes but is discarded by the final select —
    the price of one branch-free program). Returns ``[B]`` int32.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = filtered_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
