"""Paged KV cache: block-structured decode state with static XLA shapes.

The vLLM/PagedAttention (SOSP '23) memory model mapped onto a TPU-native
static-shape program — the generalization of ``GPTAttention.StaticCache``
(one contiguous ``[B, L_max, H, D]`` buffer per request) to a shared pool
of fixed-size pages:

- K/V live in ONE pool per layer, ``[num_pages, block_size, H, D]``,
  stacked ``[L, ...]`` at the model level so scan-over-layers can thread
  each layer's slice through the decode program
  (:func:`paddle_tpu.nn.scan.scan_layers_with_cache`);
- each batch slot owns a row of a **block table** ``[slots, MB]`` mapping
  logical block ``j`` (token positions ``j*bs .. j*bs+bs-1``) to a
  physical page; unallocated entries point at the reserved scratch page 0;
- pages are allocated incrementally as a request's sequence grows and
  freed the step it finishes — HBM scales with tokens actually held, not
  with ``slots * max_context`` (the fragmentation PagedAttention exists
  to kill), and the page pool size is the admission-control currency the
  scheduler trades in;
- every device shape is static: block tables and per-slot positions are
  small int32 *arguments* of the compiled step, so admitting/evicting a
  request between steps never recompiles anything.

The write/gather kernels are plain XLA scatter/gather (TPU-friendly:
one ``.at[].set`` and one ``pages[table]`` gather per layer); out-of-range
logical positions (a bucketed prefill's padded tail) route to the scratch
page by construction and are masked at read time, so no branch guards the
hot path.
"""

from __future__ import annotations

import collections
import math
from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["BlockAllocator", "PagedKVCache", "PagedCacheView",
           "PagedLayerCache", "ContextPagedCacheView",
           "ContextPagedLayerCache", "write_pages", "gather_pages",
           "write_pages_quant", "gather_pages_quant", "dequant_pages",
           "blocks_needed"]

#: physical page 0 is never allocated: it is the shared scratch target for
#: writes from inactive slots and padded prefill tails, and is masked out
#: of every read
SCRATCH_PAGE = 0


def blocks_needed(num_tokens: int, block_size: int) -> int:
    return max(0, math.ceil(int(num_tokens) / int(block_size)))


class PagedCacheView(NamedTuple):
    """Model-level traced view of the cache: what ``GPTModel.forward``
    receives as ``caches``. ``k``/``v`` are layer-stacked pools
    ``[L, P, bs, H, D]``; ``block_table`` is ``[B, MB]`` int32. Being a
    NamedTuple it is a pytree — it flows through jit/scan unchanged.

    Optional trailing fields (all default ``None`` so every pre-existing
    3-arg construction is unchanged): ``k_scale``/``v_scale`` are the
    ``[L, P, bs, H]`` f32 scale pools of a quantized cache
    (``FLAGS_serve_kv_quant``); ``lora_a``/``lora_b`` are per-layer
    stacked LoRA pools ``[L, A, r, E]`` / ``[L, A, r, O]`` and
    ``lora_ids`` the ``[B]`` int32 per-slot adapter rows (serving.lora).
    """

    k: object
    v: object
    block_table: object
    k_scale: object = None
    v_scale: object = None
    lora_a: object = None
    lora_b: object = None
    lora_ids: object = None


class PagedLayerCache(NamedTuple):
    """One layer's slice of the view (``[P, bs, H, D]`` pools), handed to
    ``GPTAttention.forward`` by both the scan body and the loop layout.
    Optional trailing fields mirror :class:`PagedCacheView` (per-layer
    slices: ``[P, bs, H]`` scales, ``[A, r, E]``/``[A, r, O]`` LoRA
    pools)."""

    k_pages: object
    v_pages: object
    block_table: object
    k_scale: object = None
    v_scale: object = None
    lora_a: object = None
    lora_b: object = None
    lora_ids: object = None


class ContextPagedCacheView(PagedCacheView):
    """Marker subtype of :class:`PagedCacheView` selecting the
    **context prefill** attention path: an S>1 chunk at per-slot
    positions ``pos`` attends over everything ALREADY IN THE PAGES
    (positions ``< pos``) as well as causally over itself — the math
    chunked prefill, prefix-cache-hit admission and speculative verify
    all need, where the plain view's S>1 path assumes ``pos == 0`` and
    attends only over its own chunk. Being a NamedTuple subtype it is
    still a pytree, and ``isinstance(x, PagedCacheView)`` still routes
    it into the paged forward; the CLASS carries the static bit, so the
    dispatch choice is resolved at trace time, never on a traced
    value."""


class ContextPagedLayerCache(PagedLayerCache):
    """One layer's slice of a :class:`ContextPagedCacheView` (same
    marker contract at the attention-block level)."""


def write_pages(pages, new, block_table, pos):
    """Scatter ``new`` ``[B, S, H, D]`` into ``pages`` ``[P, bs, H, D]``
    at logical positions ``pos[b] + 0..S-1`` through ``block_table``
    ``[B, MB]``. Positions past ``MB*bs`` (padded prefill tails) route to
    the scratch page. Returns the updated pool."""
    bs = pages.shape[1]
    mb = block_table.shape[1]
    S = new.shape[1]
    idx = pos[:, None].astype(jnp.int32) + \
        jnp.arange(S, dtype=jnp.int32)[None, :]                  # [B, S]
    blk_logical = jnp.minimum(idx // bs, mb - 1)
    blk = jnp.take_along_axis(block_table, blk_logical, axis=1)  # [B, S]
    blk = jnp.where(idx >= bs * mb, SCRATCH_PAGE, blk)
    off = idx % bs
    return pages.at[blk, off].set(new.astype(pages.dtype))


def gather_pages(pages, block_table):
    """Gather a slot-contiguous context ``[B, MB*bs, H, D]`` out of the
    pool via the block table (the PagedAttention read)."""
    g = pages[block_table]                        # [B, MB, bs, H, D]
    B, MB, bs, H, D = g.shape
    return g.reshape(B, MB * bs, H, D)


#: int8 quant range: symmetric, -127..127 (no -128 — keeps the scale
#: inversion exact under negation)
_QMAX = 127.0
#: absmax floor so an all-zero token row quantizes to scale eps, not 0/0
_QEPS = 1e-8


def write_pages_quant(pages, scales, new, block_table, pos):
    """Quantizing scatter (``FLAGS_serve_kv_quant=int8``): same indexing
    as :func:`write_pages`, but ``new`` ``[B, S, H, D]`` is stored as
    int8 in ``pages`` with a per-token-row, per-head absmax scale in the
    parallel f32 pool ``scales`` ``[P, bs, H]``. Quantization happens at
    write time — every token row is quantized exactly once, so pages can
    move between slots (COW sharing, radix donation, ``truncate_slot``,
    drain snapshots) without ever touching the payload: the scale rides
    the same physical page index. Returns ``(pages, scales)``."""
    bs = pages.shape[1]
    mb = block_table.shape[1]
    S = new.shape[1]
    idx = pos[:, None].astype(jnp.int32) + \
        jnp.arange(S, dtype=jnp.int32)[None, :]                  # [B, S]
    blk_logical = jnp.minimum(idx // bs, mb - 1)
    blk = jnp.take_along_axis(block_table, blk_logical, axis=1)  # [B, S]
    blk = jnp.where(idx >= bs * mb, SCRATCH_PAGE, blk)
    off = idx % bs
    newf = new.astype(jnp.float32)                               # [B,S,H,D]
    scale = jnp.maximum(jnp.max(jnp.abs(newf), axis=-1),
                        _QEPS) / _QMAX                           # [B,S,H]
    q = jnp.clip(jnp.round(newf / scale[..., None]),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return (pages.at[blk, off].set(q),
            scales.at[blk, off].set(scale.astype(scales.dtype)))


def dequant_pages(pages, scales):
    """Dequantize an int8 pool (or any gathered slice of one) back to
    f32: ``pages [..., H, D] * scales [..., H, None]``."""
    return pages.astype(jnp.float32) * scales.astype(jnp.float32)[..., None]


def gather_pages_quant(pages, scales, block_table):
    """Quantized PagedAttention read: gather int8 pages and their scales
    through the block table and dequantize to a slot-contiguous f32
    ``[B, MB*bs, H, D]`` context (the XLA fallback the quant Pallas
    decode kernel must match)."""
    g = dequant_pages(pages[block_table], scales[block_table])
    B, MB, bs, H, D = g.shape
    return g.reshape(B, MB * bs, H, D)


class BlockAllocator:
    """Host-side refcounted free list over the physical page pool (page
    0 reserved as scratch). O(1) alloc/incref/free; allocation is
    all-or-nothing so a half-admitted request never wedges the pool.

    Refcounts are the prefix-cache currency (ISSUE 15): a page mapped
    into N slot block tables plus the radix tree holds N+1 references;
    :meth:`free` DECREMENTS and the page only re-enters the free list
    when the count hits zero — no holder can ever see its page recycled
    under it, and a page can never be freed twice (pinned by the
    scheduler fuzz). Pages allocated by :meth:`alloc` start at count 1
    (the pre-refcount semantics: one owner, one free)."""

    def __init__(self, num_pages: int, reserved: int = 1):
        if num_pages <= reserved:
            raise ValueError(
                f"page pool of {num_pages} leaves nothing to allocate "
                f"({reserved} reserved)")
        self.num_pages = int(num_pages)
        self.reserved = int(reserved)
        self._free = collections.deque(range(reserved, num_pages))
        #: page -> reference count, for every currently-allocated page
        self._rc: dict = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.reserved - len(self._free)

    def refcount(self, page: int) -> int:
        """Current reference count (0 = on the free list)."""
        return self._rc.get(int(page), 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages at refcount 1, or None (and no change) when the pool
        cannot cover them — the scheduler's cue to wait or preempt."""
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._rc[p] = 1
        return pages

    def incref(self, page: int) -> None:
        """Add a reference to an ALLOCATED page (mapping a cached
        prefix page into another slot's block table)."""
        page = int(page)
        if page not in self._rc:
            raise ValueError(f"incref on unallocated page {page}")
        self._rc[page] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page re-enters the free list
        only when its last reference goes."""
        for p in pages:
            p = int(p)
            if not (self.reserved <= p < self.num_pages):
                raise ValueError(f"freeing page {p} outside the pool")
            rc = self._rc.get(p)
            if rc is None:
                raise ValueError(f"double free of page {p} "
                                 "(refcount already 0)")
            if rc > 1:
                self._rc[p] = rc - 1
            else:
                del self._rc[p]
                self._free.append(p)


class PagedKVCache:
    """Device page pools + host block tables for a fixed slot batch.

    ``update(new_k, new_v)`` swaps in the pools a compiled step returned;
    ``table_array()`` snapshots the host tables as the step's int32
    argument. Slot bookkeeping (``alloc_slot``/``extend_slot``/
    ``free_slot``) is pure host work — device shapes never change.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 *, num_pages: int, block_size: int, max_slots: int,
                 max_blocks_per_slot: int, dtype=jnp.float32):
        from ..core.flags import get_flag
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.dtype = jnp.dtype(dtype)
        #: quant mode, read ONCE at construction (engine convention):
        #: "" = full-precision pools (the flags-off oracle), "int8" =
        #: int8 pools + parallel f32 per-(page, row, head) scale pools;
        #: when quantized, self.k / self.v are (pages, scales) 2-tuples
        #: — pytrees, so they flow through the existing jit arg slots.
        self.quant = str(get_flag("serve_kv_quant") or "")
        if self.quant not in ("", "int8"):
            raise ValueError(
                f"FLAGS_serve_kv_quant={self.quant!r}: supported modes "
                "are '' (full precision) and 'int8'")
        shape = (num_layers, num_pages, block_size, num_heads, head_dim)
        if self.quant == "int8":
            scale_shape = shape[:-1]              # [L, P, bs, H]
            self.k = (jnp.zeros(shape, jnp.int8),
                      jnp.zeros(scale_shape, jnp.float32))
            self.v = (jnp.zeros(shape, jnp.int8),
                      jnp.zeros(scale_shape, jnp.float32))
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_pages)
        self._tables = np.full((max_slots, max_blocks_per_slot),
                               SCRATCH_PAGE, np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        #: leading pages of each slot mapped COPY-ON-WRITE from the
        #: radix prefix cache (never written by this slot: every write
        #: lands at positions >= shared * block_size)
        self._slot_shared: List[int] = [0] * max_slots
        #: optional RadixPrefixCache (serving.prefix_cache): consulted
        #: for LRU eviction when the free list cannot cover an alloc,
        #: and fed donated pages by free_slot
        self.prefix_cache = None

    # -- device-side --------------------------------------------------------
    def update(self, new_k, new_v) -> None:
        self.k, self.v = new_k, new_v

    def kv_bytes_per_token(self) -> int:
        """Device bytes ONE token position costs across all layers —
        the capacity currency the kv-quant flag halves: int8 pays
        ``H*D`` payload + ``H`` f32 scale bytes per pool, full precision
        pays ``H*D*itemsize``."""
        H, D, L = self.num_heads, self.head_dim, self.num_layers
        if self.quant == "int8":
            per_pool = H * D * 1 + H * 4
        else:
            per_pool = H * D * self.dtype.itemsize
        return 2 * L * per_pool

    def table_array(self, rows: Optional[Sequence[Optional[int]]] = None):
        """Snapshot block tables as the step's int32 argument: all slots,
        or one row per entry of ``rows`` — a ``None`` entry (a padded
        prefill row) gets an all-scratch row, so its garbage K/V can
        never land in another slot's pages."""
        if rows is None:
            return jnp.asarray(self._tables)
        t = np.full((len(rows), self.max_blocks_per_slot), SCRATCH_PAGE,
                    np.int32)
        for i, s in enumerate(rows):
            if s is not None:
                t[i] = self._tables[s]
        return jnp.asarray(t)

    @property
    def max_context_len(self) -> int:
        return self.max_blocks_per_slot * self.block_size

    # -- slot bookkeeping ---------------------------------------------------
    def slot_blocks(self, slot: int) -> int:
        return len(self._slot_pages[slot])

    def slot_shared_blocks(self, slot: int) -> int:
        """Leading COW pages mapped from the prefix cache (writes to
        this slot must start at/after ``shared * block_size``)."""
        return self._slot_shared[slot]

    def capacity_tokens(self, slot: int) -> int:
        """Token positions the slot's allocated blocks cover."""
        return self.slot_blocks(slot) * self.block_size

    def _alloc(self, n: int) -> Optional[List[int]]:
        """Allocator alloc with prefix-cache pressure relief: when the
        free list cannot cover ``n``, evict LRU radix-tree pages until
        it can (or the tree runs out) — cached prefixes are strictly
        lower-value than live requests, so they leave BEFORE any
        recompute-preemption fires."""
        pages = self.allocator.alloc(n)
        while pages is None and self.prefix_cache is not None:
            if self.prefix_cache.evict_for(
                    n - self.allocator.free_pages) <= 0:
                break
            pages = self.allocator.alloc(n)
        return pages

    def alloc_slot(self, slot: int, num_tokens: int,
                   shared_pages: Sequence[int] = ()) -> bool:
        """Allocate blocks covering ``num_tokens`` positions for a
        fresh slot. ``shared_pages`` are prefix-cache hits (already
        incref'd by the match) mapped read-only at the head of the
        block table; only the remainder is newly allocated. False when
        the pool cannot cover the remainder — the shared references are
        dropped again, so a failed admission leaks nothing."""
        if self._slot_pages[slot]:
            raise RuntimeError(f"slot {slot} already holds pages; "
                               "free_slot first")
        shared = list(shared_pages)
        need = blocks_needed(num_tokens, self.block_size)
        if len(shared) > need:
            raise ValueError(
                f"slot {slot}: {len(shared)} shared pages exceed the "
                f"{need} blocks {num_tokens} tokens need")
        pages = self._alloc(need - len(shared))
        if pages is None:
            if shared:
                self.allocator.free(shared)
            return False
        self._slot_pages[slot] = shared + pages
        self._slot_shared[slot] = len(shared)
        self._tables[slot, :need] = self._slot_pages[slot]
        return True

    def extend_slot(self, slot: int, num_tokens: int) -> bool:
        """Grow the slot to cover ``num_tokens`` positions (decode
        crossing a block boundary). False when the pool is dry — the
        preemption trigger."""
        need = blocks_needed(num_tokens, self.block_size)
        have = len(self._slot_pages[slot])
        if need <= have:
            return True
        if need > self.max_blocks_per_slot:
            raise ValueError(
                f"slot {slot}: {num_tokens} tokens exceed the "
                f"{self.max_context_len}-token slot capacity")
        pages = self._alloc(need - have)
        if pages is None:
            return False
        self._slot_pages[slot].extend(pages)
        self._tables[slot, have:need] = pages
        return True

    def truncate_slot(self, slot: int, num_tokens: int) -> int:
        """Shrink the slot to cover only ``num_tokens`` positions — the
        speculative-decode rollback: pages holding ONLY rejected draft
        K/V leave the block table and drop their reference. Never cuts
        into the COW-shared prefix (committed tokens always cover it).
        Returns the number of pages released."""
        keep = blocks_needed(num_tokens, self.block_size)
        pages = self._slot_pages[slot]
        if keep >= len(pages):
            return 0
        if keep < self._slot_shared[slot]:
            raise ValueError(
                f"slot {slot}: truncation to {num_tokens} tokens would "
                f"cut into the {self._slot_shared[slot]} shared prefix "
                "pages — committed tokens must cover the shared prefix")
        tail = pages[keep:]
        self.allocator.free(tail)
        self._slot_pages[slot] = pages[:keep]
        self._tables[slot, keep:] = SCRATCH_PAGE
        return len(tail)

    def free_slot(self, slot: int,
                  donate_tokens: Optional[Sequence[int]] = None) -> None:
        """Release the slot's pages (one reference each). With a prefix
        cache attached and ``donate_tokens`` — the token ids whose K/V
        the slot's pages VALIDLY hold, in order — full pages are donated
        into the radix tree instead (ownership of this slot's reference
        transfers; duplicates of already-cached paths are simply
        dropped), so completed/evicted requests seed future prefix
        hits."""
        pages = self._slot_pages[slot]
        if pages:
            donated = 0
            if self.prefix_cache is not None and donate_tokens is not None:
                donated = self.prefix_cache.donate(donate_tokens, pages)
            if donated < len(pages):
                self.allocator.free(pages[donated:])
        self._slot_pages[slot] = []
        self._slot_shared[slot] = 0
        self._tables[slot, :] = SCRATCH_PAGE
