"""Fleet front-end: prefix-affine routing over N serving replicas with
chaos-proof migration (ISSUE 16).

One :class:`~.engine.ServingEngine` is one host; millions of users are a
fleet. The router is the piece that makes N engines act like one
service:

- **placement** is *prefix-affine*: requests consistent-hash on their
  leading KV-page token key (block-size aligned, so two prompts that
  share a first page hash identically), which keeps a shared-prefix
  family pinned to one replica — the PR 14 radix cache then keeps
  hitting at fleet scale instead of being diluted N ways;
- **balancing** rides the PR 13 telemetry plane: a replica whose
  ``/readyz`` says draining / shedding / watchdog-tripped takes no new
  traffic, and when the affine replica is *saturated* (queue depth /
  free-page floor from its ``/statusz`` data) the router falls back to
  power-of-two-choices over the ready replicas — affinity is a
  preference, never a hot-spot guarantee;
- **migration** is the PR 8 drain path run THROUGH the router: a
  graceful drain snapshots undone work (mid-chunk prefill progress,
  trace_ids and all) and the router resubmits it on survivors via the
  same affinity policy; a replica *death* has no cooperating engine, so
  the router rebuilds each in-flight request's spec from its OWN
  streaming records (original prompt + tokens streamed so far) and
  pushes it through the same ``requests_from_snapshot`` restore —
  either way the continuation is token-exact for greedy traffic and
  no request id is dropped or duplicated.

In-process replicas (CI, bench) call the engines' readiness/status
providers directly — the very same callables the embedded admin server
exposes over HTTP — so the routing logic is identical to an
out-of-process deployment that polls ``/readyz`` + ``/statusz``.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..monitor import get_registry
from ..monitor import trace as _trace
from ..monitor.flight_recorder import safe_record_event
from .resilience import (ServerOverloaded, load_drain_snapshot,
                         requests_from_snapshot)
from .sampling import SamplingParams
from .scheduler import Request

__all__ = ["FleetRouter", "ReplicaHandle", "RouterConfig"]

#: engine outcomes the router treats as terminal for its own records
#: ("drained" is NOT here: it means the work moved to a snapshot and a
#: migration is re-homing it)
_TERMINAL_OUTCOMES = ("completed", "failed", "cancelled", "expired",
                      "shed")


class ReplicaHandle:
    """One serving replica as the router sees it: a name, a submit/step
    surface behind a lock (an engine is single-threaded), and the SAME
    readiness/status data its telemetry plane serves on ``/readyz`` and
    ``/statusz``."""

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self.alive = True
        self.lock = threading.RLock()
        #: wall time spent inside step() — the per-host busy-time model
        #: the fleet bench aggregates over (in-process CPU replicas
        #: share one GIL, so per-replica busy seconds, not router wall
        #: time, is what maps to fleet wall time on real hosts)
        self.busy_s = 0.0
        self.last_error: Optional[BaseException] = None

    def readiness(self) -> Optional[dict]:
        """None = ready (the /readyz contract); a dead replica reports
        itself the way a connection-refused poll would."""
        if not self.alive:
            return {"state": "dead"}
        return self.engine._readiness()

    def status(self) -> dict:
        """The load-relevant slice of /statusz."""
        sched = self.engine.scheduler
        return {"queue_depth": sched.queue_depth,
                "active_slots": sum(1 for s in sched.slots
                                    if s is not None),
                "free_pages": self.engine.cache.allocator.free_pages}

    def submit(self, request: Request):
        with self.lock:
            return self.engine.submit(request)

    def step(self) -> None:
        with self.lock:
            if not self.alive or not self.engine.scheduler.has_work:
                return
            t0 = time.perf_counter()
            try:
                self.engine.step()
            finally:
                self.busy_s += time.perf_counter() - t0


@dataclass
class RouterConfig:
    """Routing policy knobs."""

    #: leading KV pages of the prompt hashed as the affinity key —
    #: prompts sharing their first ``affinity_blocks`` pages land on
    #: the same replica (and therefore the same radix tree)
    affinity_blocks: int = 1
    #: ring points per replica (more = smoother key spread)
    virtual_nodes: int = 64
    #: affine replica overflows to power-of-two-choices past this
    #: queue depth ...
    saturation_queue_depth: int = 4
    #: ... or when its free KV pages drop to this floor
    saturation_free_pages: int = 0
    #: root for migration drain snapshots (per-replica subdirs); None =
    #: a tempdir is created on first graceful drain
    drain_dir: Optional[str] = None
    seed: int = 0


@dataclass
class _RouterRecord:
    """The router's own durable view of one fleet request — enough to
    rebuild its undone work WITHOUT the owning engine's cooperation
    (the replica-death path)."""

    request_id: int                     # fleet identity (first submit)
    prompt: List[int]                   # ORIGINAL prompt tokens
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: Optional[int]
    priority: int
    client_on_token: Optional[Callable]
    client_stop: Optional[Callable]
    replica: str
    tokens: List[int] = field(default_factory=list)   # streamed so far
    trace_id: Optional[str] = None
    trace: object = None                # live fleet.request Trace, if any
    trace_parent: Optional[str] = None  # last propagated parent token
    hops: int = 0                       # migrations survived
    done: bool = False
    outcome: Optional[str] = None
    state: object = None                # live RequestState, if any
    #: split-arm tag while a TrafficSplit is armed (ISSUE 20):
    #: "baseline" | "candidate" | "shadow"; None outside a bake —
    #: records without an arm emit no arm metrics (flags-off pin)
    arm: Optional[str] = None
    t_submit: float = 0.0
    #: for shadow mirrors: the primary record's request_id (greedy
    #: divergence compares the two token streams)
    shadow_of: Optional[int] = None


class FleetRouter:
    """Prefix-affine, telemetry-driven front-end over named replicas.

    ``replicas`` maps name → live :class:`~.engine.ServingEngine`.
    Synchronous driving (:meth:`run`, deterministic — chaos drills and
    the bench use it) and threaded driving (:meth:`start` /
    :meth:`join` / :meth:`stop`, one serve thread per replica) share
    the same routing and migration paths.
    """

    def __init__(self, replicas: Dict[str, object],
                 config: Optional[RouterConfig] = None,
                 clock=time.perf_counter):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.config = config or RouterConfig()
        self.clock = clock
        self.replicas: Dict[str, ReplicaHandle] = {
            name: ReplicaHandle(name, eng)
            for name, eng in replicas.items()}
        block_sizes = {h.engine.config.block_size
                       for h in self.replicas.values()}
        if len(block_sizes) != 1:
            raise ValueError(
                f"replicas disagree on block_size ({sorted(block_sizes)}); "
                "the affinity key is page-aligned and must mean the same "
                "thing fleet-wide")
        self.block_size = block_sizes.pop()
        # consistent-hash ring: virtual_nodes points per replica, built
        # once — membership changes (death/drain) are handled by
        # SKIPPING not-ready owners while walking the ring, so the keys
        # of healthy replicas never re-shuffle when one dies
        ring = []
        for name in self.replicas:
            for v in range(self.config.virtual_nodes):
                ring.append((self._hash(f"{name}#{v}".encode()), name))
        ring.sort()
        self._ring_keys = [h for h, _ in ring]
        self._ring_names = [n for _, n in ring]
        self._records: Dict[int, _RouterRecord] = {}
        self._rng = np.random.default_rng(self.config.seed ^ 0xF1EE7)
        self._route_lat: List[float] = []
        self._stats = {"routed_affine": 0, "routed_balanced": 0,
                       "rejected": 0, "migrated_drain": 0,
                       "migrated_death": 0, "migration_failed": 0,
                       "shadow_mirrored": 0, "shadow_divergence": 0}
        # shadow/A-B traffic splitting (ISSUE 20): flag read once — off
        # ⇒ set_traffic_split raises, _split stays None forever and
        # submit's only new cost is one None check (flags-off pin)
        from ..core.flags import get_flag
        self._split_enabled = bool(get_flag("serve_traffic_split"))
        self._split = None
        self._lifecycle = None
        #: shadow mirrors live OUTSIDE _records: discarded traffic must
        #: never count toward fleet availability or duplicate ids
        self._shadow_records: Dict[int, _RouterRecord] = {}
        self._divergence_pending: List[int] = []
        self._lock = threading.RLock()
        self._threads: List[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._tmp_drain_dir: Optional[str] = None
        # fleet observability plane (ISSUE 18): ONE flag read when off;
        # when on, attach this router so the federated /statusz table
        # carries the authoritative per-replica view
        from ..monitor import fleet as _fleet
        fed = _fleet.maybe_start_from_flags()
        if fed is not None and fed.router is None:
            fed.router = self

    # -- placement ----------------------------------------------------------
    @staticmethod
    def _hash(data: bytes) -> int:
        return int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "big")

    def _affinity_key(self, prompt) -> bytes:
        """The shared-prefix token key: the first ``affinity_blocks``
        KV pages' worth of prompt tokens. Page-aligned on purpose —
        radix-cache reuse is page-granular, so two prompts that would
        share cached pages hash identically."""
        n = self.block_size * self.config.affinity_blocks
        toks = np.asarray(prompt, np.int64).reshape(-1)[:n]
        return toks.tobytes()

    def _ready(self, rep: ReplicaHandle) -> bool:
        return rep.alive and rep.readiness() is None

    def _saturated(self, rep: ReplicaHandle) -> bool:
        s = rep.status()
        return (s["queue_depth"] >= self.config.saturation_queue_depth
                or s["free_pages"] <= self.config.saturation_free_pages)

    def _load(self, rep: ReplicaHandle):
        s = rep.status()
        return (s["queue_depth"] + s["active_slots"], -s["free_pages"])

    def _affine_replica(self, prompt) -> Optional[ReplicaHandle]:
        """Walk the ring clockwise from the key's position; first READY
        owner wins (dead/draining owners are skipped, their keys spill
        to the next replica on the ring — classic consistent hashing)."""
        key = self._hash(self._affinity_key(prompt))
        start = bisect.bisect_right(self._ring_keys, key)
        seen = set()
        for i in range(len(self._ring_names)):
            name = self._ring_names[(start + i) % len(self._ring_names)]
            if name in seen:
                continue
            seen.add(name)
            rep = self.replicas[name]
            if self._ready(rep):
                return rep
        return None

    def _route(self, prompt, info: Optional[dict] = None,
               exclude: Optional[str] = None) -> Optional[ReplicaHandle]:
        affine = self._affine_replica(prompt)
        if affine is not None and affine.name == exclude:
            # baseline-arm traffic keeps off the candidate replica
            # during a bake; its keys spill like a not-ready owner's
            affine = None
        if info is not None:       # tracing-only route-decision detail
            info["affinity_key"] = \
                f"{self._hash(self._affinity_key(prompt)):016x}"
        if affine is not None and not self._saturated(affine):
            self._stats["routed_affine"] += 1
            get_registry().counter(
                "serve_router_requests_total",
                "requests placed by the fleet router, by route "
                "kind").inc(route="affine")
            if info is not None:
                info["route"] = "affine"
            return affine
        if info is not None:
            info["route"] = "balanced"
            if affine is not None:
                info["fallback"] = "saturation"
        ready = [r for r in self.replicas.values()
                 if self._ready(r) and r.name != exclude]
        if not ready and exclude is not None:
            # fail open: an excluded candidate beats a shed request
            ready = [r for r in self.replicas.values() if self._ready(r)]
        if not ready:
            return None
        if len(ready) == 1:
            pick = ready[0]
        else:
            # power-of-two-choices: two distinct random candidates,
            # least-loaded wins — near-optimal balance at O(1) cost
            i, j = self._rng.choice(len(ready), size=2, replace=False)
            a, b = ready[int(i)], ready[int(j)]
            pick = a if self._load(a) <= self._load(b) else b
        self._stats["routed_balanced"] += 1
        get_registry().counter(
            "serve_router_requests_total",
            "requests placed by the fleet router, by route "
            "kind").inc(route="balanced")
        return pick

    # -- shadow/A-B traffic splitting (ISSUE 20) -----------------------------
    def replica(self, name: str) -> Optional[ReplicaHandle]:
        return self.replicas.get(name)

    def attach_lifecycle(self, controller) -> None:
        """Wire a :class:`~.lifecycle.LifecycleController`: the sweep
        reports terminal split-arm outcomes to it and :meth:`step_all`
        ticks its bake decision after each pass."""
        self._lifecycle = controller

    def set_traffic_split(self, split) -> None:
        """Arm a :class:`~.lifecycle.TrafficSplit`: live traffic
        hash-splits between the baseline replicas and the candidate
        (``ab_frac``) and/or mirrors onto the candidate with the
        mirror's response discarded but fully measured
        (``shadow_frac``). Requires ``FLAGS_serve_traffic_split`` (read
        once at router construction)."""
        if not self._split_enabled:
            raise RuntimeError(
                "FLAGS_serve_traffic_split is off — traffic splitting "
                "is disarmed for this router (the flag is read once at "
                "construction)")
        if split.candidate not in self.replicas:
            raise ValueError(
                f"traffic split candidate {split.candidate!r} is not a "
                f"replica ({sorted(self.replicas)})")
        self._split = split
        safe_record_event("traffic_split_set",
                          candidate=split.candidate,
                          ab_frac=split.ab_frac,
                          shadow_frac=split.shadow_frac)

    def clear_traffic_split(self) -> None:
        if self._split is not None:
            safe_record_event("traffic_split_cleared",
                              candidate=self._split.candidate)
        self._split = None

    def _mirror_shadow(self, rec: _RouterRecord, request: Request,
                       split) -> None:
        """Submit a shadow copy of a just-placed baseline request to
        the candidate replica. The mirror has no client callbacks (its
        response is discarded), its own request id, and its own record
        OUTSIDE the availability books; a refusal drops the mirror
        silently — shadow load must never shed live traffic."""
        cand = self.replicas.get(split.candidate)
        if cand is None or not self._ready(cand):
            return
        mirror = Request(
            prompt=np.asarray(rec.prompt, np.int32),
            max_new_tokens=rec.max_new_tokens,
            sampling=request.sampling,
            eos_token_id=request.eos_token_id,
            priority=request.priority,
            deadline_s=request.deadline_s,
            tenant=request.tenant,
            adapter=request.adapter)
        srec = _RouterRecord(
            request_id=int(mirror.request_id),
            prompt=list(rec.prompt),
            max_new_tokens=rec.max_new_tokens,
            sampling=request.sampling,
            eos_token_id=request.eos_token_id,
            priority=int(request.priority),
            client_on_token=None, client_stop=None,
            replica=cand.name, arm="shadow",
            t_submit=self.clock(), shadow_of=rec.request_id)
        mirror.on_token = self._tee(srec)
        try:
            srec.state = cand.submit(mirror)
        except (ServerOverloaded, ValueError):
            return
        with self._lock:
            self._shadow_records[srec.request_id] = srec
            self._stats["shadow_mirrored"] += 1

    def _observe_arm(self, rec: _RouterRecord, now: float) -> None:
        """Per-arm accounting for one terminal record (only called on
        arm-tagged records, so an un-split fleet emits none of these
        series)."""
        reg = get_registry()
        reg.counter(
            "serve_arm_requests_total",
            "terminal split-arm outcomes during a lifecycle "
            "bake").inc(arm=rec.arm, event=rec.outcome)
        e2e = (now - rec.t_submit) if rec.t_submit else None
        if e2e is not None:
            reg.histogram(
                "serve_arm_e2e_seconds",
                "split-arm end-to-end latency (submit -> "
                "terminal)").observe(e2e, arm=rec.arm)
        if self._lifecycle is not None:
            self._lifecycle.observe(rec.arm, rec.outcome, e2e, t=now)

    def _check_divergence(self, srec: _RouterRecord) -> bool:
        """Compare a terminal shadow mirror against its primary; True
        when settled (primary terminal too, or gone). Only greedy
        completed pairs count — sampled arms diverge by construction."""
        primary = self._records.get(srec.shadow_of)
        if primary is None:
            return True
        if not primary.done:
            return False
        if (srec.outcome == "completed"
                and primary.outcome == "completed"
                and srec.sampling.temperature == 0.0
                and srec.tokens != primary.tokens):
            self._stats["shadow_divergence"] += 1
            get_registry().counter(
                "serve_shadow_divergence_total",
                "greedy shadow mirrors whose token stream diverged "
                "from their primary's").inc()
            safe_record_event("shadow_divergence",
                              request_id=primary.request_id,
                              shadow_id=srec.request_id,
                              primary_tokens=len(primary.tokens),
                              shadow_tokens=len(srec.tokens))
        return True

    # -- submission ---------------------------------------------------------
    def _tee(self, rec: _RouterRecord) -> Callable:
        """on_token wrapper that journals every streamed token into the
        router's record (migration-by-death replays from it) before
        forwarding to the client's callback."""
        def on_token(req, token, text):
            rec.tokens.append(int(token))
            if rec.client_on_token is not None:
                rec.client_on_token(req, token, text)
        return on_token

    def submit(self, request: Request):
        """Route + submit one request. Raises
        :class:`~.resilience.ServerOverloaded` when no ready replica
        will take it (counted — availability accounting includes
        refusals)."""
        t0 = self.clock()
        tr = route_sp = info = None
        if _trace.enabled():
            # ONE distributed trace per fleet request: the router owns
            # the root ("fleet.request"); the replica's serve.request
            # tree parents under the route (or migration-hop) span via
            # the context carried on the Request. Flags off ⇒ this
            # whole branch is a single boolean read and the fast path
            # stays allocation-free (pinned by test).
            tr = _trace.get_tracer().start_trace(
                "fleet.request", trace_id=request.trace_id, t=t0,
                process="router",
                request_id=int(request.request_id),
                tenant=request.tenant)
            route_sp = tr.start_span("route", t=t0)
            info = {}
        split = self._split
        arm = None
        if split is not None:
            from .lifecycle import assign_arm, should_shadow
            arm = assign_arm(int(request.request_id), split.seed,
                             split.ab_frac)
        if arm == "candidate":
            # the A/B arm lives on the candidate replica; a not-ready
            # candidate fails open to the baseline (availability first)
            cand = self.replicas.get(split.candidate)
            if cand is not None and self._ready(cand):
                rep = cand
                if info is not None:
                    info["route"] = "ab_candidate"
            else:
                arm = "baseline"
                rep = self._route(request.prompt, info,
                                  exclude=split.candidate)
        elif arm == "baseline":
            rep = self._route(request.prompt, info,
                              exclude=split.candidate)
        else:
            rep = self._route(request.prompt, info)
        dt = self.clock() - t0
        self._route_lat.append(dt)
        get_registry().histogram(
            "serve_router_route_seconds",
            "fleet route-decision wall time").observe(dt)
        if rep is None:
            if tr is not None:
                tr.end_span(route_sp, t=t0 + dt, **info)
                tr.mark_anomaly("shed", reject="no ready replica")
                _trace.get_tracer().finish_trace(tr)
            self._reject()
            raise ServerOverloaded("no ready replica")
        if tr is not None:
            tr.end_span(route_sp, t=t0 + dt, replica=rep.name, **info)
            request.trace_id = tr.trace_id
            request.trace_parent = tr.context_for(route_sp)
            request.trace_process = rep.name
            request.trace_sampled = tr.head_sampled
        rec = _RouterRecord(
            request_id=int(request.request_id),
            prompt=[int(t) for t in
                    np.asarray(request.prompt).reshape(-1)],
            max_new_tokens=int(request.max_new_tokens),
            sampling=request.sampling,
            eos_token_id=request.eos_token_id,
            priority=int(request.priority),
            client_on_token=request.on_token,
            client_stop=request.stop,
            replica=rep.name, arm=arm, t_submit=t0,
            trace=tr, trace_parent=request.trace_parent)
        request.on_token = self._tee(rec)
        try:
            st = rep.submit(request)
        except ServerOverloaded:
            # the chosen replica refused at its own door (bounded
            # queue / overload detector): try every other ready
            # replica least-loaded-first before giving up
            for other in sorted(
                    (r for r in self.replicas.values()
                     if r is not rep and self._ready(r)),
                    key=self._load):
                try:
                    if tr is not None:
                        request.trace_process = other.name
                    st = other.submit(request)
                    if tr is not None:
                        tr.event("overflow", from_replica=rep.name,
                                 to_replica=other.name)
                    rep = other
                    break
                except ServerOverloaded:
                    continue
            else:
                if tr is not None:
                    tr.mark_anomaly("shed",
                                    reject="all replicas overloaded")
                    _trace.get_tracer().finish_trace(tr)
                self._reject()
                raise
        rec.replica = rep.name
        rec.state = st
        st_tr = getattr(st, "trace", None)
        rec.trace_id = (st_tr.trace_id if st_tr is not None
                        else request.trace_id)
        with self._lock:
            self._records[rec.request_id] = rec
        if (arm == "baseline" and split.shadow_frac > 0.0
                and rep.name != split.candidate
                and should_shadow(rec.request_id, split.seed,
                                  split.shadow_frac)):
            self._mirror_shadow(rec, request, split)
        return rec

    def _reject(self) -> None:
        with self._lock:
            self._stats["rejected"] += 1
        get_registry().counter(
            "serve_router_rejected_total",
            "requests the router could not place on any ready "
            "replica").inc()

    # -- migration ----------------------------------------------------------
    def _migration_dir(self, name: str) -> str:
        import os
        root = self.config.drain_dir
        if root is None:
            if self._tmp_drain_dir is None:
                import tempfile
                self._tmp_drain_dir = tempfile.mkdtemp(
                    prefix="ptpu_router_drain_")
            root = self._tmp_drain_dir
        return os.path.join(root, name)

    def _resubmit(self, rec: _RouterRecord, request: Request,
                  reason: str) -> bool:
        """Re-home one migrated request: affinity keyed on the ORIGINAL
        prompt (the family's radix tree, not the grown continuation),
        streaming continues into the same record, trace identity
        survives."""
        rec.state = None
        tr = rec.trace
        hop = (tr.start_span("migrate", reason=reason,
                             from_replica=rec.replica,
                             hop=rec.hops + 1,
                             tokens_streamed=len(rec.tokens))
               if tr is not None else None)
        target = self._affine_replica(rec.prompt)
        if target is None or self._saturated(target):
            picked = self._route(rec.prompt)
            target = picked if picked is not None else target
        if target is None:
            if hop is not None:
                tr.end_span(hop, outcome="failed",
                            reject="no ready replica")
            rec.done = True
            rec.outcome = "failed"
            self._stats["migration_failed"] += 1
            return False
        request.on_token = self._tee(rec)
        request.stop = rec.client_stop
        if hop is not None:
            # each hop re-parents the continuation: the survivor's
            # serve.request tree hangs off THIS migration span
            request.trace_id = tr.trace_id
            request.trace_parent = tr.context_for(hop)
            request.trace_process = target.name
            request.trace_sampled = tr.head_sampled
            rec.trace_parent = request.trace_parent
        try:
            st = target.submit(request)
        except ServerOverloaded:
            if hop is not None:
                tr.end_span(hop, outcome="failed",
                            to_replica=target.name,
                            reject="target overloaded")
            rec.done = True
            rec.outcome = "failed"
            self._stats["migration_failed"] += 1
            return False
        if hop is not None:
            tr.end_span(hop, to_replica=target.name)
        rec.replica = target.name
        rec.state = st
        rec.hops += 1
        self._stats[f"migrated_{reason}"] += 1
        get_registry().counter(
            "serve_router_migrations_total",
            "in-flight requests re-homed onto a surviving replica, by "
            "cause").inc(reason=reason)
        safe_record_event("replica_migration", reason=reason,
                          request_id=rec.request_id,
                          to_replica=target.name, hops=rec.hops,
                          tokens_streamed=len(rec.tokens))
        return True

    def drain_replica(self, name: str,
                      budget_s: Optional[float] = None) -> dict:
        """Graceful hand-off: the engine's PR 8 drain finishes what it
        can inside the budget and snapshots the rest (mid-chunk prefill
        progress, trace_ids and all); the router restores the snapshot
        through ``requests_from_snapshot`` and re-homes every spec on a
        survivor. The drained replica stays alive-but-draining (its
        /readyz already says so), taking no new traffic."""
        rep = self.replicas[name]
        snap_dir = self._migration_dir(name)
        with rep.lock:
            report = rep.engine.drain(snapshot_dir=snap_dir,
                                      budget_s=budget_s)
        moved = 0
        if report.snapshotted:
            _, specs = load_drain_snapshot(snap_dir)
            with self._lock:
                by_cur_id = {}
                for rec in self._records.values():
                    st = rec.state
                    if rec.replica == name and st is not None:
                        by_cur_id[int(st.request.request_id)] = rec
                for spec in specs:
                    rec = by_cur_id.get(int(spec["request_id"]))
                    if rec is None or rec.done:
                        continue
                    reqs = requests_from_snapshot([spec])
                    if not reqs:
                        continue
                    if self._resubmit(rec, reqs[0], reason="drain"):
                        moved += 1
        self._sweep()
        return {"replica": name, "completed": report.completed,
                "snapshotted": report.snapshotted, "migrated": moved}

    def kill_replica(self, name: str) -> int:
        """Simulated replica death (the chaos drill): NO cooperation
        from the dying engine — the router rebuilds each in-flight
        request's spec from its own streaming journal (original prompt
        + tokens already streamed to the client) and restores it
        through the same ``requests_from_snapshot`` path the drain
        uses. Committed tokens were streamed, so the continuation is
        token-exact; uncommitted work (mid-chunk prefill, staged
        drafts) recomputes on the survivor. Returns how many requests
        migrated."""
        rep = self.replicas[name]
        rep.alive = False
        with rep.lock:                   # wait out any in-flight step
            rep.engine.shutdown()        # post-mortem cleanup only
        moved = 0
        with self._lock:
            for rec in list(self._records.values()):
                if rec.replica != name or rec.done:
                    continue
                if (rec.eos_token_id is not None
                        and rec.eos_token_id in rec.tokens):
                    # the stream already ended (eos was streamed):
                    # nothing undone, just close the record
                    rec.done = True
                    rec.outcome = "completed"
                    rec.state = None
                    continue
                spec = {
                    "request_id": rec.request_id,
                    "prompt": list(rec.prompt),
                    "generated": list(rec.tokens),
                    "max_new_tokens": rec.max_new_tokens,
                    "sampling": {
                        "temperature": rec.sampling.temperature,
                        "top_k": rec.sampling.top_k,
                        "top_p": rec.sampling.top_p},
                    "eos_token_id": rec.eos_token_id,
                    "priority": rec.priority,
                }
                if rec.trace_id is not None:
                    spec["trace_id"] = rec.trace_id
                if rec.trace_parent is not None:
                    # keep the fleet parent link across the journal
                    # round-trip (the drain path carries it inside the
                    # engine snapshot already)
                    spec["trace_parent"] = rec.trace_parent
                reqs = requests_from_snapshot([spec])
                if not reqs:
                    # budget exhausted before the death: completed
                    rec.done = True
                    rec.outcome = "completed"
                    rec.state = None
                    continue
                if self._resubmit(rec, reqs[0], reason="death"):
                    moved += 1
        return moved

    # -- driving ------------------------------------------------------------
    def _sweep(self) -> None:
        """Fold engine-side completions into the router's records,
        close each finished record's fleet trace (terminal failures
        tail-retain it), and refresh the fleet gauges."""
        now = self.clock()
        with self._lock:
            for rec in self._records.values():
                st = rec.state
                if not rec.done and st is not None \
                        and st.outcome in _TERMINAL_OUTCOMES:
                    rec.done = True
                    rec.outcome = st.outcome
                    rec.state = None
                    if rec.arm is not None:
                        self._observe_arm(rec, now)
                if rec.done and rec.trace is not None:
                    tr = rec.trace
                    rec.trace = None
                    tr.root.attrs.update(outcome=rec.outcome,
                                         hops=rec.hops)
                    if rec.outcome in _trace.ANOMALY_REASONS:
                        tr.mark_anomaly(rec.outcome)
                    _trace.get_tracer().finish_trace(tr)
            # shadow mirrors fold the same way but into their own
            # books; a terminal mirror whose primary is still in
            # flight re-checks divergence on later sweeps
            for sid, srec in self._shadow_records.items():
                st = srec.state
                if not srec.done and st is not None \
                        and st.outcome in _TERMINAL_OUTCOMES:
                    srec.done = True
                    srec.outcome = st.outcome
                    srec.state = None
                    self._observe_arm(srec, now)
                    if not self._check_divergence(srec):
                        self._divergence_pending.append(sid)
            if self._divergence_pending:
                self._divergence_pending = [
                    sid for sid in self._divergence_pending
                    if not self._check_divergence(
                        self._shadow_records[sid])]

    def step_all(self) -> bool:
        """One synchronous round-robin pass over the live replicas.
        Returns whether any replica had work (False = fleet idle)."""
        worked = False
        for rep in self.replicas.values():
            if rep.alive and rep.engine.scheduler.has_work:
                rep.step()
                worked = True
        self._sweep()
        if self._lifecycle is not None:
            # bake-decision tick outside the record lock: a decision
            # touches replica engines (rollback/promotion swaps)
            self._lifecycle.maybe_decide()
        return worked

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive the fleet in the calling thread until idle
        (deterministic — drills and the bench use this mode)."""
        steps = 0
        while self.step_all():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 16,
                 sampling: Optional[SamplingParams] = None,
                 eos_token_id: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience mirroring ``ServingEngine.generate``, but
        fleet-routed: submit all, run to idle, return full sequences
        (prompt + streamed tokens) in submission order — migration-
        transparent, because the router's journal IS the stream."""
        recs = [self.submit(Request(
            p, max_new_tokens=max_new_tokens,
            sampling=sampling or SamplingParams(),
            eos_token_id=eos_token_id)) for p in prompts]
        self.run()
        return [np.asarray(rec.prompt + rec.tokens, np.int32)
                for rec in recs]

    def start(self) -> None:
        """Threaded driving: one serve loop per replica (each engine
        stays single-threaded behind its handle lock)."""
        if self._threads:
            return
        self._stop_evt.clear()
        for rep in self.replicas.values():
            t = threading.Thread(target=self._serve_loop, args=(rep,),
                                 name=f"ptpu-replica-{rep.name}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_loop(self, rep: ReplicaHandle) -> None:
        while not self._stop_evt.is_set():
            if rep.alive and rep.engine.scheduler.has_work:
                try:
                    rep.step()
                except Exception as e:      # noqa: BLE001
                    # a replica's failure must never take the router
                    # thread pool down; the engine's own fault
                    # isolation / readiness reporting covers the rest
                    rep.last_error = e
                self._sweep()
            else:
                self._stop_evt.wait(0.002)

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait until the fleet is idle (threaded mode). Returns False
        on timeout."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        while any(r.alive and r.engine.scheduler.has_work
                  for r in self.replicas.values()):
            if deadline is not None and time.perf_counter() > deadline:
                return False
            time.sleep(0.002)
        self._sweep()
        return True

    def stop(self) -> None:
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    # -- observability ------------------------------------------------------
    def _publish_fleet_gauges(self) -> None:
        reg = get_registry()
        ready = alive = 0
        for name, rep in self.replicas.items():
            if rep.alive:
                alive += 1
                if self._ready(rep):
                    ready += 1
                s = rep.status()
                ms = rep.engine.metrics_summary()
                reg.gauge(
                    "serve_router_replica_queue_depth",
                    "per-replica waiting-queue depth as the router "
                    "sees it").set(s["queue_depth"], replica=name)
                reg.gauge(
                    "serve_router_replica_prefix_hit_pct",
                    "per-replica radix prefix-cache hit percentage"
                ).set(ms.get("prefix_hit_pct") or 0.0, replica=name)
                reg.gauge(
                    "serve_router_replica_shed_requests",
                    "per-replica cumulative shed count").set(
                    rep.engine.scheduler.stats.get("shed", 0),
                    replica=name)
        reg.gauge("serve_router_replicas",
                  "fleet size by state").set(alive, state="alive")
        reg.gauge("serve_router_replicas",
                  "fleet size by state").set(ready, state="ready")

    def summary(self) -> dict:
        """Fleet-level rollup: aggregate throughput (per-host busy-time
        model), fleet prefix hit%, availability accounting (offered =
        completed + failed-ish + rejected; nothing dropped, nothing
        double-counted), migration and routing counters, and per-replica
        summaries."""
        self._sweep()
        self._publish_fleet_gauges()
        per = {}
        tot_tokens = 0
        hit_tokens = 0
        prefill_tokens = 0
        busy = []
        for name, rep in self.replicas.items():
            ms = rep.engine.metrics_summary()
            per[name] = {
                "alive": rep.alive,
                "busy_s": rep.busy_s,
                "tokens_generated": ms.get("tokens_generated", 0),
                "tokens_per_sec": ms.get("tokens_per_sec", 0.0),
                "prefix_hit_pct": ms.get("prefix_hit_pct", 0.0),
                "requests_completed": ms.get("requests_completed", 0),
                "shed": rep.engine.scheduler.stats.get("shed", 0),
            }
            tot_tokens += ms.get("tokens_generated", 0)
            hit_tokens += ms.get("prefix_hit_tokens", 0)
            prefill_tokens += ms.get("prefill_tokens", 0)
            if rep.busy_s > 0:
                busy.append(rep.busy_s)
        with self._lock:
            recs = list(self._records.values())
            shadow_recs = list(self._shadow_records.values())
            stats = dict(self._stats)
            lat = sorted(self._route_lat)
        arm_requests: Dict[str, int] = {}
        for r in recs:
            if r.arm is not None:
                arm_requests[r.arm] = arm_requests.get(r.arm, 0) + 1
        if shadow_recs:
            arm_requests["shadow"] = len(shadow_recs)
        completed = sum(1 for r in recs
                        if r.done and r.outcome == "completed")
        failed = sum(1 for r in recs
                     if r.done and r.outcome != "completed")
        in_flight = sum(1 for r in recs if not r.done)
        offered = len(recs) + stats["rejected"]
        ids = [r.request_id for r in recs]
        q = (lambda p: lat[min(len(lat) - 1,
                               int(p * (len(lat) - 1)))] if lat else 0.0)
        # per-host wall-time model: replicas on real hosts run
        # concurrently, so fleet wall time is the BUSIEST replica's
        # busy seconds (in-process CPU replicas serialize on the GIL;
        # summing their wall would charge the fleet for it)
        wall = max(busy) if busy else 0.0
        return {
            "replicas": per,
            "num_replicas": len(self.replicas),
            "alive_replicas": sum(1 for r in self.replicas.values()
                                  if r.alive),
            "tokens_generated": tot_tokens,
            "aggregate_tokens_per_sec": (tot_tokens / wall
                                         if wall > 0 else 0.0),
            "fleet_prefix_hit_pct": (
                100.0 * hit_tokens
                / max(1, hit_tokens + prefill_tokens)),
            "requests_offered": offered,
            "requests_completed": completed,
            "requests_failed": failed,
            "requests_rejected": stats["rejected"],
            "requests_in_flight": in_flight,
            "availability_pct": (100.0 * completed / offered
                                 if offered else 100.0),
            "duplicate_request_ids": len(ids) - len(set(ids)),
            "routed_affine": stats["routed_affine"],
            "routed_balanced": stats["routed_balanced"],
            "migrated_drain": stats["migrated_drain"],
            "migrated_death": stats["migrated_death"],
            "migration_failed": stats["migration_failed"],
            "route_overhead_p50_s": q(0.50),
            "route_overhead_p99_s": q(0.99),
            # model lifecycle (ISSUE 20); all zero/empty off a bake
            "arm_requests": arm_requests,
            "shadow_mirrored": stats["shadow_mirrored"],
            "shadow_divergence": stats["shadow_divergence"],
            "traffic_split": (
                {"candidate": self._split.candidate,
                 "ab_frac": self._split.ab_frac,
                 "shadow_frac": self._split.shadow_frac}
                if self._split is not None else None),
        }

    def shutdown(self) -> None:
        """Stop threads and shut every live replica down."""
        self.stop()
        self._sweep()
        with self._lock:
            for rec in self._records.values():
                # close dangling fleet traces so the tracer's live map
                # never leaks (finish_trace is idempotent)
                if rec.trace is not None:
                    tr = rec.trace
                    rec.trace = None
                    _trace.get_tracer().finish_trace(tr)
        for rep in self.replicas.values():
            if rep.alive:
                rep.alive = False
                with rep.lock:
                    rep.engine.shutdown()
