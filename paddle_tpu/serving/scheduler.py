"""Continuous-batching scheduler: iteration-level admission into fixed
batch slots.

The Orca (OSDI '22) scheduling model on static XLA shapes: scheduling
decisions happen **between** decode steps, never inside a compiled
program —

- a FIFO request queue feeds ``max_batch_slots`` fixed slots; a request
  is admitted the step a slot AND enough KV pages free up, and its slot
  is released the step it finishes (no waiting for a batch to drain —
  the throughput lever continuous batching exists for);
- admitted requests are **prefilled in bucketed groups**: the prompt
  rounds up to a ``(batch, prefill_len)`` bucket from the
  :class:`BucketTable`, so the number of distinct prefill executables is
  bounded by the table, not by traffic (decode is always the ONE
  full-slot-batch program — admission/eviction just flips the active
  mask and block tables, which are arguments);
- when the page pool runs dry mid-decode, the newest-admitted request is
  **preempted** (vLLM's recompute policy): its pages are freed, its
  prompt + tokens-so-far go back to the FRONT of the queue, and it
  re-prefills later — for greedy decoding the continuation is
  token-identical.

All of this is host-side bookkeeping over ints; device state never
changes shape.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..testing import chaos
from .kv_cache import PagedKVCache
from .resilience import ServerOverloaded
from .sampling import SamplingParams

__all__ = ["Request", "RequestState", "BucketTable", "Scheduler",
           "AdmissionGroup", "QUEUE_POLICIES", "TERMINAL_OUTCOMES"]

#: bounded-queue shedding policies (ServingConfig.queue_policy)
QUEUE_POLICIES = ("reject-new", "drop-oldest", "priority")

#: every request ends in exactly one of these (the fuzz test pins the
#: exclusivity); "completed" is the only success
TERMINAL_OUTCOMES = ("completed", "expired", "shed", "cancelled",
                     "failed", "drained")

_request_ids = itertools.count()


def _reset_request_ids() -> None:
    global _request_ids
    _request_ids = itertools.count()


@dataclass
class Request:
    """One generation request.

    ``on_token(request, token_id, text)`` streams every generated token
    the decode step it is produced (``text`` is None unless the engine
    has a detokenizer). ``eos_token_id`` ends the stream early; the eos
    token itself is reported and included in the output.

    ``deadline_s`` is a time-to-live from submission: a queued request
    past its deadline expires before it ever touches a slot; an
    in-flight one is cancelled at the next iteration boundary and its
    pages freed immediately. ``priority`` feeds the ``priority`` queue
    policy (higher = more important; ties stay FIFO). ``stop`` is an
    optional custom stop condition ``stop(generated_ids) -> bool``
    evaluated after every accepted token; a raising (malformed) stop
    condition fails ONLY its own request.

    ``trace_id`` resumes an existing trace identity under
    ``FLAGS_trace`` (drain snapshots carry it so a request's span tree
    continues on the successor engine); None = the tracer mints one.
    ``trace_parent`` / ``trace_process`` / ``trace_sampled`` are the
    rest of the cross-process trace context (ISSUE 18): the
    ``Trace.context_for`` token of the upstream (router) span this
    request's ``serve.request`` tree parents under, the replica label
    the submitter assigned this engine (one Perfetto track per
    process), and the upstream head-sampling decision — Dapper's
    sampled bit, so one coin governs every process's slice of the
    trace. All None for a bare single-engine submit.

    ``tenant`` names the submitting tenant for per-tenant quota +
    metrics (ISSUE 17; None = untenanted, never quota-limited);
    ``adapter`` names a loaded LoRA adapter (serving.lora) the request
    decodes against (None = the base model).
    """

    prompt: Sequence[int]
    max_new_tokens: int = 16
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    on_token: Optional[Callable] = None
    deadline_s: Optional[float] = None
    priority: int = 0
    stop: Optional[Callable] = None
    trace_id: Optional[str] = None
    trace_parent: Optional[str] = None
    trace_process: Optional[str] = None
    trace_sampled: Optional[bool] = None
    tenant: Optional[str] = None
    adapter: Optional[str] = None
    request_id: int = field(default_factory=lambda: next(_request_ids))

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (None = no deadline)")


class RequestState:
    """Scheduler-internal lifecycle record for one request."""

    def __init__(self, request: Request, now: float):
        self.request = request
        self.prompt_len = int(request.prompt.size)
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        self.submitted_t = now
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.preemptions = 0
        self.finished = False
        #: exactly one TERMINAL_OUTCOMES value once the request ends
        self.outcome: Optional[str] = None
        #: human-readable reason for outcome == "failed"
        self.failure: Optional[str] = None
        #: absolute wall deadline (scheduler clock domain)
        self.deadline_t: Optional[float] = (
            now + request.deadline_s if request.deadline_s is not None
            else None)
        #: client disconnect latched; honoured at the iteration boundary
        self.cancel_requested = False
        #: custom stop condition returned True (engine-evaluated)
        self.stop_hit = False
        #: chaos serve.request.poison marked this request
        self.poisoned = False
        #: prompt positions whose K/V are already in the slot's pages
        #: (ISSUE 15): admission seeds it with the prefix-cache hit
        #: length; chunked prefill advances it per chunk. Reset on
        #: preemption (the pages are gone).
        self.prefill_pos = 0
        #: weights generation this residency's KV pages were written
        #: with (ISSUE 20): stamped by the engine at the first prefill
        #: chunk, so a slot in flight across a hot swap keeps decoding
        #: on the SAME tree its pages came from. None = not stamped
        #: yet (next prefill uses the engine's live epoch). Reset on
        #: preemption — the pages are gone and the re-prefill writes
        #: fresh ones with the then-live weights.
        self.weights_epoch: Optional[int] = None
        #: effective-prompt length this residency must prefill (set at
        #: admission — effective_prompt() grows as tokens generate, so
        #: the target is stamped, not recomputed)
        self.prefill_len: Optional[int] = None
        #: speculative draft tokens proposed for the NEXT verify
        #: dispatch (uncommitted: never part of ``generated`` until the
        #: verifier accepts them; drain snapshots record them as
        #: in-flight work, restore recomputes them)
        self.draft: List[int] = []
        #: structured-tracing context (monitor/trace.py): the engine
        #: attaches a Trace + open-span handles when FLAGS_trace is on;
        #: the scheduler itself never touches them (same division of
        #: labor as the registry — the engine owns observability)
        self.trace = None
        self.trace_spans: dict = {}

    @property
    def terminal(self) -> bool:
        return self.outcome is not None

    @property
    def seq_len(self) -> int:
        """Positions currently held in the KV cache (prompt + generated
        tokens whose K/V have been written)."""
        return self.prompt_len + len(self.generated)

    def effective_prompt(self) -> np.ndarray:
        """What a (re-)prefill must process: the original prompt plus any
        tokens generated before a preemption."""
        if not self.generated:
            return self.request.prompt
        return np.concatenate([
            self.request.prompt,
            np.asarray(self.generated, np.int32)])

    def remaining_new_tokens(self) -> int:
        return self.request.max_new_tokens - len(self.generated)

    @property
    def prefilling(self) -> bool:
        """Holds a slot but has not finished its (possibly chunked)
        prefill — it takes no decode row yet."""
        return self.slot is not None and self.prefill_len is not None \
            and self.prefill_pos < self.prefill_len

    @property
    def phase(self) -> Optional[str]:
        """Slot phase for /statusz and docs/SERVING.md's state machine:
        ``prefilling`` | ``verifying`` (a speculative draft is staged
        for / aboard a verify dispatch) | ``decoding``; None while not
        resident."""
        if self.slot is None:
            return None
        if self.prefilling:
            return "prefilling"
        return "verifying" if self.draft else "decoding"

    def written_tokens(self) -> np.ndarray:
        """The token ids whose K/V this slot's pages VALIDLY hold right
        now — the prefix-cache donation payload. Mid-prefill that is
        the chunk progress; decoding it is everything but the newest
        generated token (whose K/V the next dispatch writes)."""
        eff = self.effective_prompt()
        if self.prefilling or not self.generated:
            return eff[:self.prefill_pos]
        return eff[:self.seq_len - 1]

    def is_done(self) -> bool:
        if self.stop_hit:
            return True
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return eos is not None and bool(self.generated) \
            and self.generated[-1] == eos

    def max_total_len(self) -> int:
        return self.prompt_len + self.request.max_new_tokens


class BucketTable:
    """The compile-count budget: every prefill runs at a
    ``(batch_bucket, len_bucket)`` shape from this table, so the set of
    prefill executables is bounded by ``len(batch) * len(lens)``
    regardless of traffic mix. Decode is excluded — it has exactly one
    shape (the full slot batch)."""

    def __init__(self, prefill_lens: Sequence[int],
                 batch_sizes: Sequence[int]):
        if not prefill_lens or not batch_sizes:
            raise ValueError("bucket table needs >= 1 len and batch bucket")
        self.prefill_lens = tuple(sorted(set(int(x) for x in prefill_lens)))
        self.batch_sizes = tuple(sorted(set(int(x) for x in batch_sizes)))

    @property
    def max_prefill_len(self) -> int:
        return self.prefill_lens[-1]

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def len_bucket(self, n: int) -> int:
        for b in self.prefill_lens:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds the largest "
                         f"prefill bucket ({self.max_prefill_len})")

    def batch_bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.max_batch

    def signatures(self) -> List[Tuple[int, int]]:
        return [(b, s) for s in self.prefill_lens for b in self.batch_sizes]


@dataclass
class AdmissionGroup:
    """One bucketed prefill dispatch: ``states`` (already holding slots
    and pages) padded up to ``batch_bucket`` rows at ``len_bucket``
    columns by the engine."""

    len_bucket: int
    batch_bucket: int
    states: List[RequestState]


class Scheduler:
    """FIFO queue + slot/page admission control (host-side only)."""

    def __init__(self, cache: PagedKVCache, buckets: BucketTable,
                 max_queue: int = 1024, clock=time.perf_counter,
                 max_seq_len: Optional[int] = None,
                 policy: str = "reject-new",
                 on_event: Optional[Callable] = None,
                 tenant_quota: Optional[int] = None,
                 lora=None):
        self.cache = cache
        self.buckets = buckets
        #: per-tenant fairness (ISSUE 17): max ACTIVE slots any one
        #: tenant may hold; None disables the check entirely (admission
        #: is byte-identical to the pre-quota FIFO). Untenanted requests
        #: are never limited.
        self.tenant_quota = (int(tenant_quota)
                             if tenant_quota is not None else None)
        #: optional serving.lora.LoRAManager: admission acquires the
        #: request's adapter (slot reference), slot release drops it —
        #: the refcount unload_adapter checks
        self.lora = lora
        # the admission limit is the CONFIGURED context window (position
        # embeddings!), not the cache's block-rounded physical capacity
        # which may be up to block_size-1 positions larger
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else cache.max_context_len)
        self.max_queue = int(max_queue)
        if policy not in QUEUE_POLICIES:
            raise ValueError(f"unknown queue policy {policy!r}; one of "
                             f"{QUEUE_POLICIES}")
        self.policy = policy
        #: ``on_event(outcome, state)`` fires on every terminal
        #: transition — the engine's metrics/flight hook. The scheduler
        #: itself never writes the registry (the zero-overhead pin).
        self.on_event = on_event
        self.clock = clock
        self.waiting: List[RequestState] = []
        self.slots: List[Optional[RequestState]] = \
            [None] * cache.max_slots
        self.stats = {"submitted": 0, "completed": 0, "preemptions": 0,
                      "admitted": 0, "expired": 0, "expired_queued": 0,
                      "shed": 0, "cancelled": 0, "failed": 0,
                      "drained": 0, "quota_deferred": 0}
        #: per-tenant quota-deferral counts (cumulative; the engine
        #: delta-publishes them as a labeled registry counter)
        self.tenant_deferrals: Dict[str, int] = {}
        # deadline sweeps stay O(0) until the first deadline-carrying
        # request ever arrives
        self._saw_deadline = False

    # -- terminal transitions ----------------------------------------------
    def _terminate(self, st: RequestState, outcome: str,
                   reason: Optional[str] = None) -> None:
        """The ONE exit path: frees any held slot/pages, stamps exactly
        one outcome, updates stats and fires ``on_event``."""
        assert st.outcome is None, \
            f"request {st.request.request_id} already {st.outcome}"
        if st.slot is not None:
            self._release_adapter(st)
            # prefix-cache donation (ISSUE 15): the K/V this residency
            # computed seeds future prefix hits — except a FAILED
            # request's (a non-finite forward may have written garbage)
            donate = (st.written_tokens()
                      if outcome != "failed" else None)
            self.cache.free_slot(st.slot, donate_tokens=donate)
            self.slots[st.slot] = None
            st.slot = None
        st.outcome = outcome
        st.failure = reason
        st.finished = outcome == "completed"
        st.finished_t = self.clock()
        self.stats[outcome] += 1
        if self.on_event is not None:
            self.on_event(outcome, st)

    def _shed_victim(self, request: Request) -> Optional[RequestState]:
        """Who leaves the full queue so ``request`` can enter (None =
        nobody; reject the newcomer)."""
        if self.policy == "drop-oldest":
            return self.waiting[0] if self.waiting else None
        if self.policy == "priority":
            # lowest priority first, oldest within the class — and only
            # when the newcomer actually outranks it
            victim = min(self.waiting, default=None,
                         key=lambda s: s.request.priority)
            if victim is not None \
                    and victim.request.priority < request.priority:
                return victim
        return None

    # -- queue --------------------------------------------------------------
    def submit(self, request: Request) -> RequestState:
        if request.prompt.size + request.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({request.prompt.size}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the per-slot context "
                f"capacity ({self.max_seq_len})")
        # a request that could never hold its pages even ALONE in the pool
        # would stall admission forever (alloc fails with everything free,
        # nothing to preempt) — reject it at submit, not as a livelock
        from .kv_cache import blocks_needed
        alloc = self.cache.allocator
        need = blocks_needed(request.prompt.size + request.max_new_tokens,
                             self.cache.block_size)
        if need > alloc.num_pages - alloc.reserved:
            raise ValueError(
                f"request needs {need} KV pages at full length but the "
                f"pool only holds {alloc.num_pages - alloc.reserved} — "
                "raise ServingConfig.num_pages or shrink the request")
        # the bucket table must be able to re-prefill this request even
        # after a worst-case preemption (prompt + all generated tokens)
        self.buckets.len_bucket(
            request.prompt.size + request.max_new_tokens - 1)
        # queue-full policy runs AFTER validation: an invalid request
        # must never shed a valid waiter on its way to a ValueError.
        # Sweep already-expired waiters first (O(0) without deadlines):
        # a dead request must not hold capacity against a live submit,
        # nor get mis-terminated as "shed" when it in fact expired.
        self.expire_queued()
        if len(self.waiting) >= self.max_queue:
            victim = self._shed_victim(request)
            if victim is None:
                raise ServerOverloaded("queue_full",
                                       queue_depth=len(self.waiting))
            self.waiting.remove(victim)
            self._terminate(victim, "shed")
        st = RequestState(request, self.clock())
        if st.deadline_t is not None:
            self._saw_deadline = True
        if self.policy == "priority":
            # priority lanes: insert behind the last peer of >= priority
            idx = next((i for i, w in enumerate(self.waiting)
                        if w.request.priority < request.priority),
                       len(self.waiting))
            self.waiting.insert(idx, st)
        else:
            self.waiting.append(st)
        self.stats["submitted"] += 1
        return st

    def cancel(self, request_id: int) -> bool:
        """Client disconnect: a queued request is cancelled on the spot;
        an in-flight one is latched and cancelled at the next iteration
        boundary (``sweep_active``), freeing its pages immediately then.
        False when the id is unknown or already terminal."""
        for st in self.waiting:
            if st.request.request_id == request_id:
                self.waiting.remove(st)
                self._terminate(st, "cancelled")
                return True
        for _, st in self.active():
            if st.request.request_id == request_id:
                st.cancel_requested = True
                return True
        return False

    def expire_queued(self) -> List[RequestState]:
        """Drop queued requests past their deadline — BEFORE they ever
        touch a slot (no prefill, no pages, no wasted decode work).
        O(0) until the first deadline-carrying request exists."""
        if not self._saw_deadline or not self.waiting:
            return []
        now = self.clock()
        out = []
        for st in [w for w in self.waiting
                   if w.deadline_t is not None and now >= w.deadline_t]:
            self.waiting.remove(st)
            self._terminate(st, "expired")
            # queued expiries never cost the engine any work — shed-rate
            # accounting treats them like admission drops, unlike an
            # in-flight expiry (admitted, decoded, then ran out of time)
            self.stats["expired_queued"] += 1
            out.append(st)
        return out

    def sweep_active(self) -> List[RequestState]:
        """Iteration-boundary sweep over the slots: honour latched
        cancellations and expire in-flight requests past their deadline,
        freeing their pages immediately."""
        out = []
        for _, st in list(self.active()):
            if st.cancel_requested:
                self._terminate(st, "cancelled")
                out.append(st)
            elif st.deadline_t is not None \
                    and self.clock() >= st.deadline_t:
                self._terminate(st, "expired")
                out.append(st)
        return out

    def honour_queued_cancels(self) -> List[RequestState]:
        """Terminate waiting requests whose in-flight cancel was latched
        before a preemption put them back in the queue. Admission honours
        the latch lazily (:meth:`plan_admissions`); drain calls this
        eagerly so a disconnected client's work is never snapshotted."""
        out = []
        for st in [w for w in self.waiting if w.cancel_requested]:
            self.waiting.remove(st)
            self._terminate(st, "cancelled")
            out.append(st)
        return out

    def fail(self, st: RequestState, reason: str) -> None:
        """Fault isolation: a poisoned request fails ALONE (its slot and
        pages are released; the rest of the batch streams on)."""
        self._terminate(st, "failed", reason=reason)

    def drain_release(self, st: RequestState) -> None:
        """Graceful drain: release the request (queued or in-flight)
        with outcome ``drained`` — its undone work goes to the snapshot,
        nothing is silently lost."""
        if st in self.waiting:
            self.waiting.remove(st)
        self._terminate(st, "drained")

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def oldest_waiting_t(self) -> Optional[float]:
        """``submitted_t`` of the oldest waiter, or None when the queue
        is empty. Under the ``priority`` policy the queue is lane-ordered
        (not FIFO), so the oldest waiter — the one the overload detector
        must see, or starving low-priority requests can age unboundedly
        without ever tripping it — is not necessarily ``waiting[0]``."""
        if not self.waiting:
            return None
        if self.policy == "priority":
            return min(st.submitted_t for st in self.waiting)
        return self.waiting[0].submitted_t

    def active(self) -> List[Tuple[int, RequestState]]:
        return [(i, st) for i, st in enumerate(self.slots)
                if st is not None]

    def state(self) -> dict:
        """Lifecycle snapshot for the admin plane (``/statusz`` and the
        engine's readiness reason bodies): queue depth, per-slot
        residency and the cumulative outcome stats. Called from HTTP
        handler threads at arbitrary times, so it works on one-shot
        ``list()`` copies of the queue/slot lists (atomic under the
        GIL) — the serving loop may mutate them mid-render and the
        snapshot must stay internally consistent, never raise."""
        now = self.clock()
        waiting = list(self.waiting)
        slots = list(self.slots)
        oldest = min((st.submitted_t for st in waiting), default=None)
        return {
            "queue_depth": len(waiting),
            "oldest_waiting_s": (max(0.0, now - oldest)
                                 if oldest is not None else None),
            "active_slots": sum(1 for st in slots if st is not None),
            "max_slots": len(slots),
            "slots": [
                {"slot": slot, "request_id": st.request.request_id,
                 "prompt_len": st.prompt_len,
                 "generated": len(st.generated),
                 "seq_len": st.seq_len,
                 "phase": st.phase,
                 "prefill_pos": st.prefill_pos,
                 "preemptions": st.preemptions}
                for slot, st in enumerate(slots) if st is not None],
            "stats": dict(self.stats),
        }

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            st is not None for st in self.slots)

    # -- admission ----------------------------------------------------------
    def plan_admissions(self) -> List[RequestState]:
        """Admit as many waiting requests as slots + pages allow, FIFO:
        slot assigned, pages allocated for the effective prompt (with
        any prefix-cache hit mapped COW), ``prefill_pos``/``prefill_len``
        stamped. Returns the newly admitted states in admission order —
        grouping them into bucketed prefill dispatches is the engine's
        job (``ServingEngine._plan_prefill_groups``, ONE grouping path
        that also carries chunked-prefill continuations)."""
        admitted: List[Tuple[int, RequestState]] = []
        free_slots = [i for i, st in enumerate(self.slots) if st is None]
        if self.waiting and free_slots and chaos.active() \
                and chaos.probe("serve.pages.exhaust"):
            return []                  # injected dry pool: admission waits
        # idx scans past quota-blocked requests (per-tenant fairness,
        # ISSUE 17) so one tenant at its cap cannot head-of-line-block
        # every other tenant; without a quota idx never advances and the
        # loop is the pre-quota FIFO exactly
        idx = 0
        while free_slots and idx < len(self.waiting):
            st = self.waiting[idx]
            if st.cancel_requested:
                # a latched in-flight cancel survives preemption back to
                # the queue: honour it here, never waste a prefill on it
                self.waiting.pop(idx)
                self._terminate(st, "cancelled")
                continue
            if self.tenant_quota is not None \
                    and st.request.tenant is not None \
                    and self._tenant_active(st.request.tenant) \
                    >= self.tenant_quota:
                self.stats["quota_deferred"] += 1
                t = st.request.tenant
                self.tenant_deferrals[t] = \
                    self.tenant_deferrals.get(t, 0) + 1
                idx += 1               # skip; later tenants still admit
                continue
            if st.request.adapter and (
                    self.lora is None
                    or self.lora.row(st.request.adapter) is None):
                # the adapter was unloaded (or never loaded) between
                # submit and admission: fail THIS request alone rather
                # than decode it against the zero adapter silently
                self.waiting.pop(idx)
                self._terminate(
                    st, "failed",
                    reason=f"adapter {st.request.adapter!r} not loaded")
                continue
            slot = free_slots[0]
            eff = st.effective_prompt()
            # radix prefix cache (ISSUE 15): map the longest cached
            # page-aligned prefix copy-on-write into the block-table
            # head; the slot prefills only the tail. match() incref'd
            # the hit pages; a failed alloc drops them again inside
            # alloc_slot, so the retry next iteration re-matches.
            n_hit, shared = 0, ()
            if self.cache.prefix_cache is not None:
                n_hit, shared = self.cache.prefix_cache.match(eff)
            if not self.cache.alloc_slot(slot, eff.size,
                                         shared_pages=shared):
                break                      # page pool dry: FIFO blocks
            self.waiting.pop(idx)
            free_slots.pop(0)
            st.slot = slot
            st.admitted_t = self.clock()
            st.prefill_pos = n_hit
            st.prefill_len = int(eff.size)
            self.slots[slot] = st
            if self.lora is not None and st.request.adapter:
                self.lora.acquire(st.request.adapter)
            admitted.append((slot, st))
            self.stats["admitted"] += 1
        return [st for _, st in admitted]

    # -- decode-time growth / preemption ------------------------------------
    def ensure_decode_capacity(self) -> List[RequestState]:
        """Before a decode step, make sure every active slot has a page
        for the position it is about to write (``seq_len``). On a dry
        pool, preempt newest-admitted requests (recompute policy) until
        the older ones fit. Returns the preempted states (already
        requeued at the queue front)."""
        preempted: List[RequestState] = []
        if len(self.active()) >= 2 and chaos.active() \
                and chaos.probe("serve.pages.exhaust"):
            # injected pool pressure: recompute-preempt the newest
            # admitted request (token-identical continuation for greedy)
            # — the same victim order as the real dry-pool path below;
            # the oldest is excluded so the batch always keeps progress
            oldest = min(self.active(),
                         key=lambda p: p[1].admitted_t)[1]
            victim = self._newest_active(exclude=oldest)
            if victim is not None:
                self._preempt(victim)
                preempted.append(victim)
        # oldest-first: earlier-admitted requests keep their pages
        order = sorted(self.active(), key=lambda p: p[1].admitted_t)
        for slot, st in order:
            if self.slots[slot] is not st:
                continue                       # preempted below, skip
            # this decode step writes position seq_len-1 (the newest
            # generated token's K/V) -> the slot must cover seq_len
            # positions; a staged speculative draft writes its k tokens
            # at the following positions, so the slot must also cover
            # them BEFORE the verify dispatch (a draft's K/V must never
            # spill into the shared scratch page — rows of the verify
            # window read it back)
            while not self.cache.extend_slot(
                    slot, st.seq_len + len(st.draft)):
                victim = self._newest_active(exclude=st)
                if victim is None:
                    raise RuntimeError(
                        "KV page pool too small for a single request: "
                        f"{st.seq_len} tokens need more pages than "
                        "the pool holds — raise num_pages or shrink "
                        "max_new_tokens")
                self._preempt(victim)
                preempted.append(victim)
        return preempted

    def _newest_active(self, exclude: RequestState) \
            -> Optional[RequestState]:
        cands = [st for _, st in self.active() if st is not exclude]
        if not cands:
            return None
        return max(cands, key=lambda s: s.admitted_t)

    def _release_adapter(self, st: RequestState) -> None:
        """Drop the slot's LoRA adapter reference (acquired at
        admission) — called from BOTH slot-release paths
        (:meth:`_terminate`, :meth:`_preempt`), so the reference
        invariant is exactly "held iff resident"."""
        if self.lora is not None and st.request.adapter:
            self.lora.release(st.request.adapter)

    def _tenant_active(self, tenant: str) -> int:
        """Slots currently held by ``tenant`` (the quota currency)."""
        return sum(1 for st in self.slots
                   if st is not None and st.request.tenant == tenant)

    def _preempt(self, st: RequestState, count: bool = True) -> None:
        assert st.slot is not None
        self._release_adapter(st)
        # evicted residencies donate too (vLLM/SGLang recompute policy
        # meets the radix cache): the pages stay warm in the tree, so a
        # re-admission — or any sibling sharing the prefix — hits them
        # instead of re-prefilling; allocation pressure evicts them LRU
        self.cache.free_slot(st.slot,
                             donate_tokens=st.written_tokens())
        self.slots[st.slot] = None
        st.slot = None
        st.admitted_t = None
        st.prefill_pos = 0
        st.prefill_len = None
        st.weights_epoch = None
        st.draft = []
        if count:
            st.preemptions += 1
            self.stats["preemptions"] += 1
        if self.policy == "priority":
            # front of its priority class (ahead of equal-priority
            # waiters: it already held a slot once)
            idx = next((i for i, w in enumerate(self.waiting)
                        if w.request.priority <= st.request.priority),
                       len(self.waiting))
            self.waiting.insert(idx, st)
        else:
            self.waiting.insert(0, st)         # reclaims FIFO priority

    def rollback_admission(self, sts: Sequence[RequestState]) -> None:
        """Un-admit freshly admitted states whose prefill never produced
        a token (watchdog trip abandoned the dispatch): back to the
        queue front, pages freed, so a retried ``step()`` re-plans the
        admission and re-prefills instead of decoding slots that have no
        generated token to feed. Reversed so FIFO order survives the
        one-at-a-time front inserts. Not counted as a preemption — the
        page-pressure telemetry must not read watchdog incidents as a
        dry KV pool."""
        for st in reversed(list(sts)):
            if st.slot is not None and self.slots[st.slot] is st:
                self._preempt(st, count=False)

    # -- completion ---------------------------------------------------------
    def finish(self, st: RequestState) -> None:
        assert st.slot is not None
        self._terminate(st, "completed")
