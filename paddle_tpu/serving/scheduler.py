"""Continuous-batching scheduler: iteration-level admission into fixed
batch slots.

The Orca (OSDI '22) scheduling model on static XLA shapes: scheduling
decisions happen **between** decode steps, never inside a compiled
program —

- a FIFO request queue feeds ``max_batch_slots`` fixed slots; a request
  is admitted the step a slot AND enough KV pages free up, and its slot
  is released the step it finishes (no waiting for a batch to drain —
  the throughput lever continuous batching exists for);
- admitted requests are **prefilled in bucketed groups**: the prompt
  rounds up to a ``(batch, prefill_len)`` bucket from the
  :class:`BucketTable`, so the number of distinct prefill executables is
  bounded by the table, not by traffic (decode is always the ONE
  full-slot-batch program — admission/eviction just flips the active
  mask and block tables, which are arguments);
- when the page pool runs dry mid-decode, the newest-admitted request is
  **preempted** (vLLM's recompute policy): its pages are freed, its
  prompt + tokens-so-far go back to the FRONT of the queue, and it
  re-prefills later — for greedy decoding the continuation is
  token-identical.

All of this is host-side bookkeeping over ints; device state never
changes shape.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .kv_cache import PagedKVCache
from .sampling import SamplingParams

__all__ = ["Request", "RequestState", "BucketTable", "Scheduler",
           "AdmissionGroup"]

_request_ids = itertools.count()


def _reset_request_ids() -> None:
    global _request_ids
    _request_ids = itertools.count()


@dataclass
class Request:
    """One generation request.

    ``on_token(request, token_id, text)`` streams every generated token
    the decode step it is produced (``text`` is None unless the engine
    has a detokenizer). ``eos_token_id`` ends the stream early; the eos
    token itself is reported and included in the output.
    """

    prompt: Sequence[int]
    max_new_tokens: int = 16
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    on_token: Optional[Callable] = None
    request_id: int = field(default_factory=lambda: next(_request_ids))

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class RequestState:
    """Scheduler-internal lifecycle record for one request."""

    def __init__(self, request: Request, now: float):
        self.request = request
        self.prompt_len = int(request.prompt.size)
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        self.submitted_t = now
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.preemptions = 0
        self.finished = False

    @property
    def seq_len(self) -> int:
        """Positions currently held in the KV cache (prompt + generated
        tokens whose K/V have been written)."""
        return self.prompt_len + len(self.generated)

    def effective_prompt(self) -> np.ndarray:
        """What a (re-)prefill must process: the original prompt plus any
        tokens generated before a preemption."""
        if not self.generated:
            return self.request.prompt
        return np.concatenate([
            self.request.prompt,
            np.asarray(self.generated, np.int32)])

    def remaining_new_tokens(self) -> int:
        return self.request.max_new_tokens - len(self.generated)

    def is_done(self) -> bool:
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return eos is not None and bool(self.generated) \
            and self.generated[-1] == eos

    def max_total_len(self) -> int:
        return self.prompt_len + self.request.max_new_tokens


class BucketTable:
    """The compile-count budget: every prefill runs at a
    ``(batch_bucket, len_bucket)`` shape from this table, so the set of
    prefill executables is bounded by ``len(batch) * len(lens)``
    regardless of traffic mix. Decode is excluded — it has exactly one
    shape (the full slot batch)."""

    def __init__(self, prefill_lens: Sequence[int],
                 batch_sizes: Sequence[int]):
        if not prefill_lens or not batch_sizes:
            raise ValueError("bucket table needs >= 1 len and batch bucket")
        self.prefill_lens = tuple(sorted(set(int(x) for x in prefill_lens)))
        self.batch_sizes = tuple(sorted(set(int(x) for x in batch_sizes)))

    @property
    def max_prefill_len(self) -> int:
        return self.prefill_lens[-1]

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def len_bucket(self, n: int) -> int:
        for b in self.prefill_lens:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds the largest "
                         f"prefill bucket ({self.max_prefill_len})")

    def batch_bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.max_batch

    def signatures(self) -> List[Tuple[int, int]]:
        return [(b, s) for s in self.prefill_lens for b in self.batch_sizes]


@dataclass
class AdmissionGroup:
    """One bucketed prefill dispatch: ``states`` (already holding slots
    and pages) padded up to ``batch_bucket`` rows at ``len_bucket``
    columns by the engine."""

    len_bucket: int
    batch_bucket: int
    states: List[RequestState]


class Scheduler:
    """FIFO queue + slot/page admission control (host-side only)."""

    def __init__(self, cache: PagedKVCache, buckets: BucketTable,
                 max_queue: int = 1024, clock=time.perf_counter,
                 max_seq_len: Optional[int] = None):
        self.cache = cache
        self.buckets = buckets
        # the admission limit is the CONFIGURED context window (position
        # embeddings!), not the cache's block-rounded physical capacity
        # which may be up to block_size-1 positions larger
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else cache.max_context_len)
        self.max_queue = int(max_queue)
        self.clock = clock
        self.waiting: List[RequestState] = []
        self.slots: List[Optional[RequestState]] = \
            [None] * cache.max_slots
        self.stats = {"submitted": 0, "completed": 0, "preemptions": 0,
                      "admitted": 0}

    # -- queue --------------------------------------------------------------
    def submit(self, request: Request) -> RequestState:
        if len(self.waiting) >= self.max_queue:
            raise RuntimeError(f"request queue full ({self.max_queue})")
        if request.prompt.size + request.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({request.prompt.size}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the per-slot context "
                f"capacity ({self.max_seq_len})")
        # a request that could never hold its pages even ALONE in the pool
        # would stall admission forever (alloc fails with everything free,
        # nothing to preempt) — reject it at submit, not as a livelock
        from .kv_cache import blocks_needed
        alloc = self.cache.allocator
        need = blocks_needed(request.prompt.size + request.max_new_tokens,
                             self.cache.block_size)
        if need > alloc.num_pages - alloc.reserved:
            raise ValueError(
                f"request needs {need} KV pages at full length but the "
                f"pool only holds {alloc.num_pages - alloc.reserved} — "
                "raise ServingConfig.num_pages or shrink the request")
        # the bucket table must be able to re-prefill this request even
        # after a worst-case preemption (prompt + all generated tokens)
        self.buckets.len_bucket(
            request.prompt.size + request.max_new_tokens - 1)
        st = RequestState(request, self.clock())
        self.waiting.append(st)
        self.stats["submitted"] += 1
        return st

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def active(self) -> List[Tuple[int, RequestState]]:
        return [(i, st) for i, st in enumerate(self.slots)
                if st is not None]

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            st is not None for st in self.slots)

    # -- admission ----------------------------------------------------------
    def plan_admissions(self) -> List[AdmissionGroup]:
        """Admit as many waiting requests as slots + pages allow, FIFO,
        and group them into bucketed prefill dispatches. Allocation is
        done here (slot assigned, pages for the effective prompt), so a
        returned group is guaranteed runnable."""
        admitted: List[Tuple[int, RequestState]] = []
        free_slots = [i for i, st in enumerate(self.slots) if st is None]
        while self.waiting and free_slots:
            st = self.waiting[0]
            slot = free_slots[0]
            if not self.cache.alloc_slot(slot, st.effective_prompt().size):
                break                      # page pool dry: FIFO blocks
            self.waiting.pop(0)
            free_slots.pop(0)
            st.slot = slot
            st.admitted_t = self.clock()
            self.slots[slot] = st
            admitted.append((slot, st))
            self.stats["admitted"] += 1
        groups: List[AdmissionGroup] = []
        by_len = {}
        for slot, st in admitted:
            lb = self.buckets.len_bucket(st.effective_prompt().size)
            by_len.setdefault(lb, []).append(st)
        for lb in sorted(by_len):
            sts = by_len[lb]
            mb = self.buckets.max_batch
            for i in range(0, len(sts), mb):
                chunk = sts[i:i + mb]
                groups.append(AdmissionGroup(
                    lb, self.buckets.batch_bucket(len(chunk)), chunk))
        return groups

    # -- decode-time growth / preemption ------------------------------------
    def ensure_decode_capacity(self) -> List[RequestState]:
        """Before a decode step, make sure every active slot has a page
        for the position it is about to write (``seq_len``). On a dry
        pool, preempt newest-admitted requests (recompute policy) until
        the older ones fit. Returns the preempted states (already
        requeued at the queue front)."""
        preempted: List[RequestState] = []
        # oldest-first: earlier-admitted requests keep their pages
        order = sorted(self.active(), key=lambda p: p[1].admitted_t)
        for slot, st in order:
            if self.slots[slot] is not st:
                continue                       # preempted below, skip
            # this decode step writes position seq_len-1 (the newest
            # generated token's K/V) -> the slot must cover seq_len
            # positions
            while not self.cache.extend_slot(slot, st.seq_len):
                victim = self._newest_active(exclude=st)
                if victim is None:
                    raise RuntimeError(
                        "KV page pool too small for a single request: "
                        f"{st.seq_len} tokens need more pages than "
                        "the pool holds — raise num_pages or shrink "
                        "max_new_tokens")
                self._preempt(victim)
                preempted.append(victim)
        return preempted

    def _newest_active(self, exclude: RequestState) \
            -> Optional[RequestState]:
        cands = [st for _, st in self.active() if st is not exclude]
        if not cands:
            return None
        return max(cands, key=lambda s: s.admitted_t)

    def _preempt(self, st: RequestState) -> None:
        assert st.slot is not None
        self.cache.free_slot(st.slot)
        self.slots[st.slot] = None
        st.slot = None
        st.admitted_t = None
        st.preemptions += 1
        self.stats["preemptions"] += 1
        self.waiting.insert(0, st)             # reclaims FIFO priority

    # -- completion ---------------------------------------------------------
    def finish(self, st: RequestState) -> None:
        assert st.slot is not None
        self.cache.free_slot(st.slot)
        self.slots[st.slot] = None
        st.slot = None
        st.finished = True
        st.finished_t = self.clock()
        self.stats["completed"] += 1
