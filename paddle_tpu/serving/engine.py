"""TPU-native LLM serving engine: paged decode + continuous batching
behind AOT-compiled serving signatures.

The execution model (ISSUE 6; docs/SERVING.md):

- **two program kinds**, split the way TPU serving wants them: a
  *prefill* program per ``(batch, prefill_len)`` bucket (prompt forward,
  K/V written into the paged pools, first token sampled) and ONE
  *decode* program over the full slot batch (single-token forward via
  the block tables, next token sampled per slot, inactive slots masked).
  Both are built with :class:`paddle_tpu.jit.aot.AOTProgram` — the same
  lower/compile machinery as ``TrainStep`` — so executables exist before
  traffic arrives (``warmup()``) and per-program HBM/FLOPs attribution
  comes from the exact executables that serve;
- **continuous batching**: the :class:`~.scheduler.Scheduler` admits and
  evicts requests between decode steps; every decode dispatch serves
  whatever mix of requests currently holds slots (block tables, write
  positions, sampling params and the active mask are all ARGUMENTS, so
  membership changes never recompile);
- **decode under scan**: with ``FLAGS_scan_decode`` (default on) the
  layer stack runs as one ``lax.scan`` threading each layer's K/V pages
  (``nn.scan.scan_layers_with_cache``) — O(1) trace/compile in depth,
  same as training;
- **telemetry**: per-request TTFT / TPOT / end-to-end latency and
  queue/occupancy gauges stream into the ``paddle_tpu.monitor`` registry
  (serving metrics are always on — an engine exists to be observed; the
  FLAGS_monitor zero-write contract covers the *training* hot path), and
  ``metrics_summary()`` computes the p50/p99 numbers ``bench.py
  --serve`` records.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, no_grad
from ..core.random import trace_rng
from ..jit.aot import AOTProgram
from ..jit.functional import bind, buffer_arrays, param_arrays
from ..monitor import get_registry
from ..monitor import flight_recorder as _flight
from ..monitor import trace as _trace
from ..testing import chaos
from .detok import StreamingDetokenizer
from .kv_cache import (ContextPagedCacheView, PagedCacheView,
                       PagedKVCache, blocks_needed)
from .resilience import (DecodeWatchdogError, DispatchWorker, DrainLatch,
                         DrainReport, EngineDrained, OverloadDetector,
                         ServerOverloaded, request_spec,
                         requests_from_snapshot, save_drain_snapshot)
from .sampling import (SamplingParams, _NEG as _SAMPLING_NEG,
                       filtered_logits, sample_tokens)
from .scheduler import (QUEUE_POLICIES, AdmissionGroup, BucketTable,
                        Request, RequestState, Scheduler)

__all__ = ["ServingConfig", "ServingEngine", "WeightSwapError"]


class WeightSwapError(RuntimeError):
    """A candidate weight push was refused (torn manifest, param-tree
    mismatch, unreadable checkpoint) or a rollback had nothing retained
    to roll back to. Refusal is side-effect free: the serving weights
    did not change and traffic keeps flowing on the old tree."""

    def __init__(self, manifest_dir: str, reason: str):
        super().__init__(
            f"weight swap refused for {manifest_dir!r}: {reason}")
        self.manifest_dir = manifest_dir
        self.reason = reason

#: live engines, for test isolation (serving.reset shuts them down)
_LIVE_ENGINES: "weakref.WeakSet[ServingEngine]" = weakref.WeakSet()

#: trace/compile serialization across engines (see _mesh_scope): the
#: fleet router's per-replica serve threads must not trace concurrently
_COMPILE_LOCK = threading.Lock()


def _pow2_buckets(lo: int, hi: int) -> Tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


@dataclass
class ServingConfig:
    """Engine sizing + policy.

    ``max_context_len`` bounds prompt+generation per request;
    ``num_pages`` sizes the shared KV pool (default: full residency for
    every slot, i.e. no preemption pressure — shrink it to trade HBM for
    recompute-preemptions). ``prefill_buckets``/``batch_buckets`` ARE the
    compile budget: one prefill executable per pair actually used.
    """

    max_batch_slots: int = 8
    block_size: int = 16
    max_context_len: int = 512
    num_pages: Optional[int] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    batch_buckets: Tuple[int, ...] = (1, 2, 4)
    max_queue: int = 1024
    seed: int = 0
    cache_dtype: str = "float32"
    detokenizer: Optional[StreamingDetokenizer] = None
    #: bounded-queue shedding policy: reject-new | drop-oldest | priority
    queue_policy: str = "reject-new"
    #: queue-delay EWMA overload detector: > 0 arms it — while the EWMA
    #: of head-of-queue delay exceeds this, every new submit is shed
    #: with a typed ServerOverloaded. 0 (default) = detector off.
    overload_threshold_s: float = 0.0
    overload_alpha: float = 0.3
    overload_exit_frac: float = 0.5
    #: SLO objectives (monitor/slo.py): fractions in (0,1) arming the
    #: multi-window error-budget burn trackers — availability over
    #: request outcomes, deadline over completion slack. 0.0 (default)
    #: = no tracker, zero extra work per request.
    slo_availability: float = 0.0
    slo_deadline: float = 0.0
    slo_windows: Tuple[float, ...] = (60.0, 300.0, 3600.0)
    #: graceful-drain grace period: how long a drain keeps decoding
    #: in-flight sequences before snapshotting the rest
    drain_budget_s: float = 5.0
    #: where drain snapshots commit (drain_<n> dirs); None = drain()
    #: refuses to discard pending work
    drain_dir: Optional[str] = None
    #: tensor-parallel serving mesh (ISSUE 16): a jax Mesh whose ``mp``
    #: axis shards attention heads / MLP width across chips. The serving
    #: signatures compile under it (collectives live INSIDE the
    #: executables, via the model's Megatron specs + GSPMD) and the
    #: paged K/V pools shard over the heads dim
    #: (distributed.spmd.SERVE_KV_SPEC) — per-chip HBM holds 1/mp of
    #: params and KV, which is what serves models beyond one chip.
    #: None (default) = single-chip engine, bit-compatible.
    mesh: Optional[object] = None
    #: multi-tenant LoRA (ISSUE 17): > 0 builds a serving.lora
    #: LoRAManager with this many loadable adapter rows and threads the
    #: stacked pools + per-slot adapter ids through every serving
    #: program (the bgmv path). 0 (default) = no manager, program
    #: signatures and dispatch args unchanged — bit-compatible.
    lora_adapters: int = 0
    lora_rank: int = 8
    #: per-tenant admission cap: at most this many slots may hold
    #: requests of one tenant at a time (excess waits in the queue while
    #: OTHER tenants admit past it — the fairness floor). None
    #: (default) = no cap, admission order is byte-identical FIFO.
    tenant_quota: Optional[int] = None

    def resolve(self, model_max_positions: Optional[int]) -> None:
        if self.queue_policy not in QUEUE_POLICIES:
            raise ValueError(
                f"unknown queue_policy {self.queue_policy!r}; one of "
                f"{QUEUE_POLICIES}")
        if model_max_positions is not None:
            self.max_context_len = min(self.max_context_len,
                                       int(model_max_positions))
        if self.prefill_buckets is None:
            lo = min(max(self.block_size, 16), self.max_context_len)
            self.prefill_buckets = _pow2_buckets(lo, self.max_context_len)
        else:
            self.prefill_buckets = tuple(
                min(int(b), self.max_context_len)
                for b in self.prefill_buckets)
            if max(self.prefill_buckets) < self.max_context_len:
                # preemption re-prefills prompt+generated-so-far; the
                # table must cover the worst case
                self.prefill_buckets += (self.max_context_len,)
        self.batch_buckets = tuple(
            min(int(b), self.max_batch_slots) for b in self.batch_buckets)
        if self.num_pages is None:
            per_slot = blocks_needed(self.max_context_len, self.block_size)
            self.num_pages = 1 + self.max_batch_slots * per_slot


class ServingEngine:
    """Serve a decoder-only model (GPT-style ``forward(input_ids,
    caches=<PagedCacheView>, cache_pos=<[B] positions>)`` returning
    ``(logits, new_caches)``) with continuous batching."""

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 clock=time.perf_counter):
        self.model = model
        cfg = getattr(model, "cfg", None)
        if cfg is None:
            raise ValueError("ServingEngine needs a model with a .cfg "
                             "(num_heads/head_dim/num_layers)")
        import dataclasses
        # resolve() fills model-dependent defaults — work on a copy so a
        # caller-owned config can be reused across engines/models
        self.config = dataclasses.replace(config) if config is not None \
            else ServingConfig()
        self.config.resolve(getattr(cfg, "max_position_embeddings", None))
        self.clock = clock
        model.eval()
        self.mesh = self.config.mesh
        if self.mesh is not None:
            # TP-sharded serving (ISSUE 16): stamp Megatron specs on any
            # params still unplaced and lay the model out on the mesh
            # BEFORE param_arrays snapshots it, so every AOT serving
            # program compiles against sharded donors and GSPMD bakes
            # the collectives into the executables.
            from ..distributed.spmd import (apply_hybrid_specs,
                                            apply_param_shardings)
            mp = dict(self.mesh.shape).get("mp", 1)
            if cfg.num_heads % mp:
                raise ValueError(
                    f"model num_heads={cfg.num_heads} not divisible by "
                    f"mesh mp={mp}; TP serving shards KV over heads")
            apply_hybrid_specs(model)
            apply_param_shardings(model, self.mesh)
        self.params = param_arrays(model)
        self.buffers = buffer_arrays(model)
        c = self.config
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_heads, cfg.head_dim,
            num_pages=c.num_pages, block_size=c.block_size,
            max_slots=c.max_batch_slots,
            max_blocks_per_slot=blocks_needed(c.max_context_len,
                                              c.block_size),
            dtype=jnp.dtype(c.cache_dtype))
        if self.mesh is not None:
            from ..distributed.spmd import shard_serving_cache
            shard_serving_cache(self.cache, self.mesh)
        self.buckets = BucketTable(c.prefill_buckets, c.batch_buckets)
        self.lora = None
        if c.lora_adapters > 0:
            from .lora import LoRAManager
            # pools sized to the fused-QKV delta (3*H*D out features) —
            # built BEFORE the scheduler, which acquires/releases
            # adapter references at the slot lifecycle choke points
            self.lora = LoRAManager(
                cfg.num_layers, cfg.hidden_size,
                3 * cfg.num_heads * cfg.head_dim,
                max_adapters=c.lora_adapters, rank=c.lora_rank)
        self.scheduler = Scheduler(self.cache, self.buckets,
                                   max_queue=c.max_queue, clock=clock,
                                   max_seq_len=c.max_context_len,
                                   policy=c.queue_policy,
                                   on_event=self._on_request_event,
                                   tenant_quota=c.tenant_quota,
                                   lora=self.lora)
        self._overload = (OverloadDetector(
            c.overload_threshold_s, alpha=c.overload_alpha,
            exit_frac=c.overload_exit_frac)
            if c.overload_threshold_s > 0 else None)
        from ..monitor.slo import SLOTracker
        self._slo_avail = (SLOTracker(
            "serve_availability", c.slo_availability,
            windows=c.slo_windows, clock=clock)
            if c.slo_availability > 0 else None)
        self._slo_deadline = (SLOTracker(
            "serve_deadline", c.slo_deadline,
            windows=c.slo_windows, clock=clock)
            if c.slo_deadline > 0 else None)
        # throughput features (ISSUE 15), each behind its own
        # kill-switch flag with the flags-off path bit-compatible; read
        # ONCE here so an engine's behavior (and its compiled program
        # set) is stable for its lifetime — tests flip them with
        # flag_scope around construction
        from ..core.flags import get_flag
        self._chunk = int(get_flag("serve_prefill_chunk") or 0)
        self._spec_k = int(get_flag("serve_spec_k") or 0)
        self._spec_ngram = max(1, int(get_flag("serve_spec_ngram") or 1))
        self.prefix_cache = None
        if bool(get_flag("serve_prefix_cache")):
            from .prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(self.cache)
            self.cache.prefix_cache = self.prefix_cache
        self._prefix_published: Dict[str, float] = {}
        #: delta-publish cursors for the per-tenant counters (same
        #: pattern as the prefix metrics: host stats are the source of
        #: truth, the registry sees monotone deltas)
        self._quota_published: Dict[str, int] = {}
        self._drain_latch: Optional[DrainLatch] = None
        self._draining = False
        self._drained = False
        self._watchdog_threads: List[threading.Thread] = []
        self._watchdog_worker: Optional[DispatchWorker] = None
        self._programs: Dict[tuple, AOTProgram] = {}
        self._programs_info: Dict[str, dict] = {}
        self._key = jax.random.key(int(c.seed))
        #: host-side accept/reject coin for stochastic speculative
        #: sampling (ISSUE 16) — its own stream so spec on/off never
        #: perturbs the device RNG the flags-off oracle pins
        self._spec_rng = np.random.default_rng((int(c.seed) << 1) ^ 0x51EC)
        self._dispatch_seq = 0
        self._stats = {"prefill_dispatches": 0, "decode_dispatches": 0,
                       "decode_slot_steps": 0, "decode_batch_max": 0,
                       "tokens_generated": 0, "program_compiles": 0,
                       "prefill_chunks": 0, "prefill_tokens": 0,
                       "verify_dispatches": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "spec_rolled_back": 0}
        self._lat: Dict[str, List[float]] = {
            "ttft": [], "tpot": [], "e2e": [], "decode_step": []}
        self._t_first_work: Optional[float] = None
        self._t_last_token: Optional[float] = None
        #: last watchdog trip (kind/timeout/dispatch) — readiness
        #: reports it until a later guarded dispatch succeeds
        self._watchdog_tripped: Optional[dict] = None
        # model lifecycle (ISSUE 20): live weight hot-swap. Flag read
        # once, same contract as the throughput features above — off ⇒
        # swap_weights raises, _retired stays empty forever and every
        # dispatch takes the single-epoch path (byte-identical to the
        # pre-lifecycle engine).
        self._hot_swap = bool(get_flag("serve_hot_swap"))
        #: live weights generation; bumped at every cutover (including
        #: rollback cutovers). Slots are stamped with the epoch whose
        #: tree wrote their first KV page and finish on that tree.
        self._weights_epoch = 0
        #: candidate staged for the next iteration-boundary cutover
        self._staged: Optional[dict] = None
        #: epoch -> param tree still referenced by in-flight slots
        self._retired: Dict[int, dict] = {}
        #: pre-swap tree retained as the rollback anchor until
        #: commit_swap() drops it (promotion) or rollback_weights()
        #: re-stages it
        self._previous: Optional[dict] = None
        self._live_manifest: Optional[str] = None
        self._swap_stats = {"staged": 0, "cutover": 0, "refused": 0,
                            "rolled_back": 0, "committed": 0,
                            "drain_swaps": 0}
        _LIVE_ENGINES.add(self)
        self._attach_admin()

    # -- live telemetry plane (monitor/server.py; ISSUE 14) ------------------
    def _attach_admin(self) -> None:
        """Join the embedded admin plane when ``FLAGS_monitor_port`` is
        set: /readyz derives from THIS engine's state machine
        (draining/shedding/watchdog-tripped ⇒ 503) and /statusz gains a
        section with scheduler occupancy, program attribution and SLO
        burn. Flag unset (default) = one flag read, no thread, no
        socket, no registry writes — the zero-overhead contract."""
        from ..monitor import server as monitor_server
        self._admin = monitor_server.maybe_start_from_flags()
        self._admin_key = f"serving_engine_{id(self)}"
        if self._admin is None:
            return
        # weakref'd providers: a collected engine returns the STALE
        # sentinel so the server PRUNES the registration — never None,
        # which /readyz would read as "ready" (fail-open). Explicit
        # shutdown() unregisters instead: that is the drain hand-off,
        # where the successor engine's own registration takes over.
        ref = weakref.ref(self)
        stale = monitor_server.STALE
        self._admin.register_readiness(
            self._admin_key,
            lambda: (lambda e: stale if e is None else e._readiness())(
                ref()))
        self._admin.register_status(
            self._admin_key,
            lambda: (lambda e: stale if e is None
                     else e._admin_status())(ref()))

    def _readiness(self) -> Optional[dict]:
        """None while this engine should receive traffic; otherwise a
        JSON reason derived from the serving state machine
        (docs/SERVING.md): the load balancer's signal to pull this
        replica. Reads live state only — a state transition is visible
        to /readyz within the same iteration it happens."""
        if self._drained:
            return {"state": "drained",
                    "detail": "engine drained; hand traffic to the "
                              "successor"}
        if self._draining or (self._drain_latch is not None
                              and self._drain_latch.triggered):
            return {"state": "draining",
                    "queue_depth": self.scheduler.queue_depth,
                    "active_slots": len(self.scheduler.active())}
        if self._overload is not None and self._overload.overloaded:
            return {"state": "shedding",
                    "ewma_s": round(self._overload.ewma_s, 4),
                    "threshold_s": self._overload.threshold_s,
                    "queue_depth": self.scheduler.queue_depth}
        if self._watchdog_tripped is not None:
            return dict(self._watchdog_tripped,
                        state="watchdog-tripped")
        return None

    def _admin_status(self) -> dict:
        """/statusz section: the live engine picture an operator reads
        before deciding to drain/restart — occupancy, outcome stats,
        program FLOPs/HBM attribution, SLO burn."""
        d: dict = {
            "scheduler": self.scheduler.state(),
            "kv_pages_in_use": self.cache.allocator.pages_in_use,
            "kv_pages_total": self.cache.allocator.num_pages,
            "engine_stats": dict(self._stats),
            "programs": dict(self._programs_info),
            "draining": self._draining,
            "drained": self._drained,
            "overloaded": (self._overload.overloaded
                           if self._overload is not None else False),
            "watchdog_tripped": self._watchdog_tripped,
        }
        if self._hot_swap:
            d["weights"] = {
                "epoch": self._weights_epoch,
                "live_manifest": self._live_manifest,
                "staged": (self._staged["manifest"]
                           if self._staged is not None else None),
                "retired_epochs": sorted(self._retired),
                "rollback_available": self._previous is not None,
                "swaps": dict(self._swap_stats),
            }
        if self.lora is not None:
            d["lora"] = {
                "loaded": self.lora.loaded(),
                "swaps": self.lora.swaps,
                "refcounts": {n: self.lora.refcount(n)
                              for n in self.lora.loaded()},
            }
        if self.scheduler.tenant_quota is not None:
            d["tenant_deferrals"] = dict(self.scheduler.tenant_deferrals)
        if self._slo_avail is not None:
            d["slo_availability"] = self._slo_avail.snapshot()
        if self._slo_deadline is not None:
            d["slo_deadline"] = self._slo_deadline.snapshot()
        return d

    def _detach_admin(self) -> None:
        admin = getattr(self, "_admin", None)
        if admin is not None:
            admin.unregister_readiness(self._admin_key)
            admin.unregister_status(self._admin_key)
            self._admin = None

    # -- program construction ----------------------------------------------
    def _next_key(self):
        self._dispatch_seq += 1
        return jax.random.fold_in(self._key, self._dispatch_seq)

    @contextlib.contextmanager
    def _mesh_scope(self):
        """Activate the TP serving mesh for the dynamic extent of a
        program trace/compile: ``constrain()`` pins inside the model
        read the active mesh at TRACE time, so without this scope a
        TP engine's programs would silently compile unsharded. Dispatch
        needs no scope — the compiled executables embed their shardings.

        Also serializes traces process-wide: the fleet router drives
        one serve thread per replica, and two replicas lazily compiling
        at once would race on the global mesh (and on trace-time global
        state generally). The lock is only ever taken on a compile
        miss, never on the dispatch path."""
        with _COMPILE_LOCK:
            if self.mesh is None:
                yield
                return
            from ..distributed import env as dist_env
            prev = dist_env.get_mesh()
            dist_env.set_mesh(self.mesh)
            try:
                yield
            finally:
                dist_env.set_mesh(prev)

    def _fwd(self, params, ids, k, v, table, pos, lora=None,
             ctx: bool = False):
        """Pure model forward over the paged view (traced inside the
        prefill/decode programs). ``ctx=True`` selects the
        CONTEXT-prefill attention path (ISSUE 15): S>1 chunks attend
        over everything already in the pages, not just themselves —
        chunked-prefill continuations, prefix-hit tails and speculative
        verify windows all run through it.

        Quantized pools (FLAGS_serve_kv_quant) arrive as
        ``(pages, scales)`` tuples and leave the same way, so
        ``cache.update`` keeps the tuple structure; ``lora`` is the
        optional ``(a_pool, b_pool, per_slot_rows)`` triple of a
        multi-tenant engine (ISSUE 17) — the view carries it down to
        the attention blocks' bgmv delta."""
        cls = ContextPagedCacheView if ctx else PagedCacheView
        quant = isinstance(k, tuple)
        wrap = lambda t: None if t is None else Tensor(t)
        la, lb, rows = lora if lora is not None else (None, None, None)
        if quant:
            view = cls(Tensor(k[0]), Tensor(v[0]), Tensor(table),
                       Tensor(k[1]), Tensor(v[1]), wrap(la), wrap(lb),
                       wrap(rows))
        else:
            view = cls(Tensor(k), Tensor(v), Tensor(table), None, None,
                       wrap(la), wrap(lb), wrap(rows))
        with bind(self.model, params, dict(self.buffers)), no_grad(), \
                trace_rng(jax.random.key(0)):
            logits, new = self.model(Tensor(ids), caches=view,
                                     cache_pos=Tensor(pos))
        unw = lambda t: t._data if isinstance(t, Tensor) else t
        if quant:
            return (unw(logits), (unw(new.k), unw(new.k_scale)),
                    (unw(new.v), unw(new.v_scale)))
        return unw(logits), unw(new.k), unw(new.v)

    def _attribute(self, kind: str, lowered, compiled) -> None:
        """Per-program attribution from the serving executables (same
        sources as TrainStep: lowered.cost_analysis /
        compiled.memory_analysis)."""
        self._stats["program_compiles"] += 1
        entry: dict = {}
        try:
            from ..cost_model import CostModel
            entry = CostModel().attribute(lowered)
        except Exception:
            pass
        try:
            from ..monitor import memory as monitor_memory
            pm = monitor_memory.analyze_compiled(compiled, kind=kind)
            if pm is not None:
                entry["peak_hbm_bytes"] = pm.peak_bytes
                monitor_memory.record_program(pm)
                get_registry().gauge(
                    "serve_program_peak_hbm_bytes",
                    "static peak-HBM estimate per serving program"
                ).set(pm.peak_bytes, kind=kind)
        except Exception:
            pass
        self._programs_info[kind] = entry
        get_registry().counter(
            "serve_program_compiles_total",
            "serving executable builds by program kind").inc(kind=kind)

    def _donate(self) -> tuple:
        from ..core.flags import get_flag
        from ..jit.to_static import _donation_safe
        # pools are the 2nd/3rd argument of both program kinds; donation
        # keeps decode's HBM footprint at ONE pool copy (skipped on the
        # cpu+persistent-cache test backend — the jax 0.4.37 scan+donate
        # aliasing hazard, see _donation_safe). An armed watchdog also
        # disables donation: a tripped dispatch is ABANDONED mid-flight,
        # and retrying the step is only sound while the live pools are
        # neither invalidated (donated away) nor mutated in place by the
        # zombie thread — the documented trade is one extra pool copy
        # for retryable trips.
        if float(get_flag("serve_watchdog_s") or 0.0) > 0.0:
            return ()
        return (1, 2) if _donation_safe() else ()

    def _get_decode(self) -> AOTProgram:
        key = ("decode",)
        prog = self._programs.get(key)
        if prog is not None:
            return prog

        def decode_fn(params, k, v, table, pos, tokens, active, rng,
                      temps, top_ks, top_ps, poison, *lora):
            # *lora is (a_pool, b_pool, rows) on a multi-tenant engine
            # and EMPTY otherwise — the 12-arg signature and the traced
            # program are unchanged when FLAGS/config leave LoRA off
            logits, k, v = self._fwd(params, tokens[:, None], k, v,
                                     table, pos, lora=lora or None)
            # poison is all-zeros outside chaos (bit-transparent); a NaN
            # entry models a slot whose forward went non-finite. `ok` is
            # the per-slot fault-isolation flag: one bad request fails
            # alone, the rest of the batch streams on.
            row = logits[:, -1, :] + poison[:, None]
            ok = jnp.isfinite(row).all(axis=-1)
            toks = sample_tokens(row, rng, temps, top_ks, top_ps)
            return jnp.where(active, toks, 0), ok, k, v

        B = self.config.max_batch_slots
        mb = self.cache.max_blocks_per_slot
        prog = AOTProgram("serve_decode", decode_fn,
                          donate_argnums=self._donate(),
                          on_attribute=self._attribute)
        with self._mesh_scope():
            prog.compile((self.params, self.cache.k, self.cache.v,
                          jnp.zeros((B, mb), jnp.int32),
                          jnp.zeros((B,), jnp.int32),
                          jnp.zeros((B,), jnp.int32),
                          jnp.zeros((B,), bool), self._key,
                          jnp.ones((B,), jnp.float32),
                          jnp.zeros((B,), jnp.int32),
                          jnp.ones((B,), jnp.float32),
                          jnp.zeros((B,), jnp.float32))
                         + self._lora_sig(B))
        self._programs[key] = prog
        return prog

    def _lora_sig(self, n: int) -> tuple:
        """Compile-time LoRA argument suffix for an ``n``-row program:
        the stacked pools + an all-zero (= zero-adapter) row vector.
        Empty on a non-LoRA engine — signatures stay pinned."""
        if self.lora is None:
            return ()
        return (self.lora.a, self.lora.b, jnp.zeros((n,), jnp.int32))

    def _lora_args(self, states) -> tuple:
        """Dispatch-time LoRA argument suffix: the LIVE pools (hot-swaps
        between steps are just new arguments — never a recompile) and
        each row's adapter pool index (empty slots / base requests ride
        the zero adapter, row 0)."""
        if self.lora is None:
            return ()
        rows = self.lora.rows_for(
            [st.request.adapter if st is not None else None
             for st in states])
        return (self.lora.a, self.lora.b, rows)

    def _get_prefill(self, nb: int, sp: int) -> AOTProgram:
        key = ("prefill", nb, sp)
        prog = self._programs.get(key)
        if prog is not None:
            return prog

        def prefill_fn(params, k, v, table, ids, lens, rng, temps,
                       top_ks, top_ps, poison, *lora):
            pos = jnp.zeros((nb,), jnp.int32)
            logits, k, v = self._fwd(params, ids, k, v, table, pos,
                                     lora=lora or None)
            last = jnp.take_along_axis(
                logits, (lens - 1).astype(jnp.int32)[:, None, None],
                axis=1)[:, 0, :]
            row = last + poison[:, None]
            ok = jnp.isfinite(row).all(axis=-1)
            toks = sample_tokens(row, rng, temps, top_ks, top_ps)
            return toks, ok, k, v

        mb = self.cache.max_blocks_per_slot
        prog = AOTProgram(f"serve_prefill_b{nb}_s{sp}", prefill_fn,
                          donate_argnums=self._donate(),
                          on_attribute=self._attribute)
        with self._mesh_scope():
            prog.compile((self.params, self.cache.k, self.cache.v,
                          jnp.zeros((nb, mb), jnp.int32),
                          jnp.zeros((nb, sp), jnp.int32),
                          jnp.ones((nb,), jnp.int32), self._key,
                          jnp.ones((nb,), jnp.float32),
                          jnp.zeros((nb,), jnp.int32),
                          jnp.ones((nb,), jnp.float32),
                          jnp.zeros((nb,), jnp.float32))
                         + self._lora_sig(nb))
        self._programs[key] = prog
        return prog

    def _get_prefill_ctx(self, nb: int, sp: int) -> AOTProgram:
        """Context-prefill program (ISSUE 15): same shape contract as
        the plain prefill bucket, plus a per-row ``pos`` argument — the
        chunk's rows occupy positions ``pos .. pos+lens-1`` and attend
        over every page-resident position before them. Serves chunked-
        prefill continuation chunks and prefix-cache-hit tails."""
        key = ("prefill_ctx", nb, sp)
        prog = self._programs.get(key)
        if prog is not None:
            return prog

        def prefill_ctx_fn(params, k, v, table, ids, lens, pos, rng,
                           temps, top_ks, top_ps, poison, *lora):
            logits, k, v = self._fwd(params, ids, k, v, table, pos,
                                     lora=lora or None, ctx=True)
            last = jnp.take_along_axis(
                logits, (lens - 1).astype(jnp.int32)[:, None, None],
                axis=1)[:, 0, :]
            row = last + poison[:, None]
            ok = jnp.isfinite(row).all(axis=-1)
            toks = sample_tokens(row, rng, temps, top_ks, top_ps)
            return toks, ok, k, v

        mb = self.cache.max_blocks_per_slot
        prog = AOTProgram(f"serve_prefill_ctx_b{nb}_s{sp}",
                          prefill_ctx_fn,
                          donate_argnums=self._donate(),
                          on_attribute=self._attribute)
        with self._mesh_scope():
            prog.compile((self.params, self.cache.k, self.cache.v,
                          jnp.zeros((nb, mb), jnp.int32),
                          jnp.zeros((nb, sp), jnp.int32),
                          jnp.ones((nb,), jnp.int32),
                          jnp.zeros((nb,), jnp.int32), self._key,
                          jnp.ones((nb,), jnp.float32),
                          jnp.zeros((nb,), jnp.int32),
                          jnp.ones((nb,), jnp.float32),
                          jnp.zeros((nb,), jnp.float32))
                         + self._lora_sig(nb))
        self._programs[key] = prog
        return prog

    def _get_verify(self) -> AOTProgram:
        """Speculative-verify program (ISSUE 15): ONE dispatch scores
        all ``k+1`` positions of ``[last_token, d_1 .. d_k]`` per slot
        against the paged cache. Returns the row-0 token under each
        slot's sampling params (== the plain decode output), per-row
        greedy argmaxes for draft acceptance, and per-row finite flags
        (fault isolation stays per-slot AND per-used-row — pad rows
        beyond a slot's draft may read scratch garbage and are never
        consulted). For sampled slots (ISSUE 16) it additionally
        returns the residual accept/reject ingredients — the drafted
        token's probability under each row's FILTERED sampling
        distribution, a full fresh sample per row (bonus token on a
        clean sweep), and a residual redraw per row with the draft
        masked out — so the host can run point-mass-drafter
        Leviathan-style acceptance and the committed stream keeps the
        plain sampled-decode distribution exactly."""
        key = ("verify", self._spec_k + 1)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        S = self._spec_k + 1

        def verify_fn(params, k, v, table, pos, ids, active, rng,
                      temps, top_ks, top_ps, poison, *lora):
            logits, k, v = self._fwd(params, ids, k, v, table, pos,
                                     lora=lora or None,
                                     ctx=True)                # [B,S,V]
            row0 = logits[:, 0, :] + poison[:, None]
            ok_rows = jnp.isfinite(logits).all(axis=-1)       # [B,S]
            ok_rows = ok_rows.at[:, 0].set(
                jnp.isfinite(row0).all(axis=-1))
            tok0 = sample_tokens(row0, rng, temps, top_ks, top_ps)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            B, V = logits.shape[0], logits.shape[-1]
            flat = filtered_logits(
                logits.reshape(B * S, V).astype(jnp.float32),
                jnp.repeat(temps, S), jnp.repeat(top_ks, S),
                jnp.repeat(top_ps, S)).reshape(B, S, V)
            probs = jax.nn.softmax(flat, axis=-1)
            drafts = ids[:, 1:]                               # [B,S-1]
            # p_draft[b, i] = P(draft_i | rows 0..i) — row i's filtered
            # softmax mass on the token the drafter proposed for it
            p_draft = jnp.take_along_axis(
                probs[:, :-1, :], drafts[..., None],
                axis=-1)[..., 0]                              # [B,S-1]
            k_full, k_resid = jax.random.split(jax.random.fold_in(rng, 1))
            tok_full = jax.random.categorical(
                k_full, flat, axis=-1).astype(jnp.int32)      # [B,S]
            resid = jnp.where(
                jax.nn.one_hot(drafts, V, dtype=bool),
                _SAMPLING_NEG, flat[:, :-1, :])
            tok_resid = jax.random.categorical(
                k_resid, resid, axis=-1).astype(jnp.int32)    # [B,S-1]
            return (jnp.where(active, tok0, 0), greedy, ok_rows,
                    p_draft, tok_full, tok_resid, k, v)

        B = self.config.max_batch_slots
        mb = self.cache.max_blocks_per_slot
        prog = AOTProgram(f"serve_verify_s{S}", verify_fn,
                          donate_argnums=self._donate(),
                          on_attribute=self._attribute)
        with self._mesh_scope():
            prog.compile((self.params, self.cache.k, self.cache.v,
                          jnp.zeros((B, mb), jnp.int32),
                          jnp.zeros((B,), jnp.int32),
                          jnp.zeros((B, S), jnp.int32),
                          jnp.zeros((B,), bool), self._key,
                          jnp.ones((B,), jnp.float32),
                          jnp.zeros((B,), jnp.int32),
                          jnp.ones((B,), jnp.float32),
                          jnp.zeros((B,), jnp.float32))
                         + self._lora_sig(B))
        self._programs[key] = prog
        return prog

    def warmup(self, prefill_signatures: Optional[Sequence[Tuple[int, int]]]
               = None) -> int:
        """AOT-compile the decode program and the given (or full bucket
        table's) prefill signatures before traffic arrives — plus, when
        the ISSUE 15 features are armed, the context-prefill twins and
        the speculative-verify program, so the first prefix hit / chunk
        continuation / draft never pays a cold compile. Returns the
        number of programs now resident."""
        self._get_decode()
        for nb, sp in (prefill_signatures
                       if prefill_signatures is not None
                       else self.buckets.signatures()):
            self._get_prefill(nb, sp)
            if self._chunk > 0 or self.prefix_cache is not None:
                self._get_prefill_ctx(nb, sp)
        if self._spec_k > 0:
            self._get_verify()
        return len(self._programs)

    #: raw latency samples kept per series for exact percentiles; beyond
    #: this the oldest half is dropped (a long-running engine must not
    #: grow host memory per request — summaries then cover the recent
    #: window, which is what an SLO dashboard wants anyway)
    LAT_WINDOW = 65536

    def _observe(self, series: str, value: float) -> None:
        lst = self._lat[series]
        lst.append(value)
        if len(lst) > 2 * self.LAT_WINDOW:
            del lst[:len(lst) - self.LAT_WINDOW]

    #: deadline-slack buckets: negatives = finished past deadline (only
    #: possible within one iteration of it), small positives = tight SLO
    DEADLINE_SLACK_BUCKETS = (-1.0, -0.1, 0.0, 0.05, 0.1, 0.25, 0.5,
                              1.0, 2.0, 5.0, 30.0)

    def _requests_counter(self):
        return get_registry().counter(
            "serve_requests_total",
            "serving requests by lifecycle event")

    def _on_request_event(self, outcome: str, st: RequestState) -> None:
        """Scheduler terminal-transition hook: metrics + forensics +
        span-tree closure. Only fires on lifecycle events — never per
        step (the zero-overhead pin)."""
        self._requests_counter().inc(event=outcome)
        if st.request.tenant:
            get_registry().counter(
                "serve_tenant_requests_total",
                "serving requests by tenant and lifecycle event").inc(
                tenant=st.request.tenant, event=outcome)
        if outcome != "completed":
            self._flight_event(
                "request_failed" if outcome == "failed"
                else f"request_{outcome}",
                request_id=st.request.request_id,
                reason=st.failure, tokens=len(st.generated),
                preemptions=st.preemptions)
        if self._slo_avail is not None:
            # availability: cancelled/drained are client/operator
            # choices, not served-badly outcomes — they spend no budget
            if outcome == "completed":
                self._slo_avail.record(good=1)
            elif outcome in ("expired", "failed", "shed"):
                self._slo_avail.record(bad=1)
            self._slo_avail.publish()
        if self._slo_deadline is not None and outcome == "expired":
            # an expiry is a blown deadline whether queued or in-flight
            # (the completed-on-time case is fed from _accept_token)
            self._slo_deadline.record(bad=1)
            self._slo_deadline.publish()
        self._close_trace(st, outcome)

    def _close_trace(self, st: RequestState, outcome: str) -> None:
        """Terminal span + retention decision for a traced request (the
        ``Scheduler._terminate`` seam: every exit path lands here)."""
        tr = st.trace
        if tr is None:
            return
        now = self.clock()
        for key in ("queued", "admitted"):
            sp = st.trace_spans.pop(key, None)
            if sp is not None:
                tr.end_span(sp, t=now)
        tr.event("terminal", t=now, outcome=outcome,
                 reason=st.failure, tokens=len(st.generated),
                 preemptions=st.preemptions)
        if outcome in ("expired", "shed", "failed"):
            reason = st.failure or ""
            tr.mark_anomaly(
                "nonfinite" if "non-finite" in reason
                else ("chaos" if st.poisoned
                      else ("failed" if outcome == "failed"
                            else outcome)),
                failure=st.failure)
        tr.root.set_attrs(outcome=outcome)
        _trace.get_tracer().finish_trace(tr, t=now)
        st.trace_spans.clear()

    @staticmethod
    def _flight_enabled() -> bool:
        return _flight.enabled()

    @staticmethod
    def _flight_event(name: str, **fields) -> None:
        _flight.safe_record_event(name, **fields)

    # -- request surface ----------------------------------------------------
    def submit(self, request: Request) -> RequestState:
        if self._draining or self._drained:
            self._requests_counter().inc(event="rejected")
            raise ServerOverloaded("draining")
        if self._overload is not None and self._overload.overloaded:
            # recovery samples normally arrive from step(), but step()
            # is only driven while there is work — an IDLE engine must
            # fold the (empty-queue = 0 delay) sample here or a tripped
            # detector latches forever and sheds all future traffic
            if not self.scheduler.has_work:
                transition = self._overload.observe(0.0)
                if transition is not None:
                    self._overload_transition(transition)
            if self._overload.overloaded:
                self._requests_counter().inc(event="rejected")
                raise ServerOverloaded(
                    "overload", queue_depth=self.scheduler.queue_depth,
                    ewma_s=self._overload.ewma_s,
                    threshold_s=self._overload.threshold_s)
        if request.adapter and (
                self.lora is None
                or self.lora.row(request.adapter) is None):
            # fail fast at the door: a request naming an unknown
            # adapter can never decode (the scheduler re-checks at
            # admission, covering a hot-unload that races the queue)
            self._requests_counter().inc(event="rejected")
            raise ValueError(
                f"adapter {request.adapter!r} is not loaded"
                + ("" if self.lora is not None
                   else " (engine has no LoRA manager; set "
                        "ServingConfig.lora_adapters)"))
        try:
            st = self.scheduler.submit(request)
        except ServerOverloaded:
            # bounded queue refused the newcomer (policy produced no
            # victim). A never-admitted refusal counts as "rejected";
            # "shed" is reserved for admitted-then-evicted policy
            # victims, so offered = submitted + rejected stays exact.
            self._requests_counter().inc(event="rejected")
            raise
        if chaos.active() and chaos.probe("serve.request.poison"):
            st.poisoned = True
        if _trace.enabled():
            # one trace per request; a drain-snapshot trace_id RESUMES
            # the identity on this (successor) engine. Tail-based
            # retention needs the buffer regardless of the head coin,
            # so the trace exists for every request while the flag is
            # on — the flag OFF path allocates nothing (pinned).
            # a bare trace_id is a drain/resume identity handover; one
            # arriving WITH a parent token is just downstream context
            # from the fleet router — not a resume
            resumed = (request.trace_id is not None
                       and request.trace_parent is None)
            tr = _trace.get_tracer().start_trace(
                "serve.request", trace_id=request.trace_id,
                # the upstream (router) head decision wins when the
                # context carries one — Dapper's sampled bit, ONE coin
                # per distributed trace. Otherwise a resumed identity
                # was handed over deliberately (its first half may
                # already be retained) — never let a re-flip of the
                # head coin drop the continuation. All spans run on the
                # ENGINE clock (t=): injectable in tests, one time
                # domain per trace.
                sample=(request.trace_sampled
                        if request.trace_sampled is not None
                        else (True if resumed else None)),
                t=st.submitted_t,
                # cross-process parent link + producing-replica label
                # (ISSUE 18): the fleet merge parents this tree under
                # the router's route/hop span and renders it on this
                # replica's own Perfetto track
                process=request.trace_process,
                parent=request.trace_parent,
                request_id=request.request_id,
                prompt_len=st.prompt_len,
                max_new_tokens=request.max_new_tokens,
                resumed=resumed)
            st.trace = tr
            st.trace_spans["queued"] = tr.start_span(
                "queued", t=st.submitted_t)
            if st.poisoned:
                tr.mark_anomaly("chaos",
                                chaos_site="serve.request.poison")
        self._requests_counter().inc(event="submitted")
        if request.tenant:
            # emits-metrics: serve_tenant_requests_total
            get_registry().counter(
                "serve_tenant_requests_total",
                "serving requests by tenant and lifecycle event").inc(
                tenant=request.tenant, event="submitted")
        self._publish_gauges()
        return st

    def cancel(self, request_id: int) -> bool:
        """Client disconnect: cancel a queued request immediately or an
        in-flight one at the next iteration boundary (its pages are
        freed there). Returns False for unknown/terminal ids."""
        hit = self.scheduler.cancel(request_id)
        if hit:
            self._publish_gauges()
        return hit

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 16,
                 sampling: Optional[SamplingParams] = None,
                 eos_token_id: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience: submit, drain, return full sequences
        (prompt + generated) per request, in submission order."""
        states = [self.submit(Request(
            p, max_new_tokens=max_new_tokens,
            sampling=sampling or SamplingParams(),
            eos_token_id=eos_token_id)) for p in prompts]
        self.run()
        return [np.concatenate([st.request.prompt,
                                np.asarray(st.generated, np.int32)])
                for st in states]

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive the scheduler until the queue and slots drain. Raises
        :class:`EngineDrained` if a latched drain signal is honoured
        mid-run."""
        steps = 0
        while self.scheduler.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return

    # -- graceful drain ------------------------------------------------------
    def enable_drain(self, snapshot_dir: str,
                     budget_s: Optional[float] = None,
                     signals=None) -> DrainLatch:
        """Install the shutdown latch (PR 5 pattern): SIGTERM (default)
        is latched by a thin handler and honoured at the next iteration
        boundary — :meth:`step` then drains and raises
        :class:`EngineDrained`. Returns the latch (``trigger()`` arms it
        programmatically; ``shutdown()`` restores the handlers)."""
        import signal as signal_mod
        self.config.drain_dir = snapshot_dir
        if budget_s is not None:
            self.config.drain_budget_s = float(budget_s)
        if self._drain_latch is not None:
            self._drain_latch.close()
        self._drain_latch = DrainLatch(
            signals if signals is not None else (signal_mod.SIGTERM,))
        return self._drain_latch

    def drain(self, snapshot_dir: Optional[str] = None,
              budget_s: Optional[float] = None) -> DrainReport:
        """Graceful shutdown: stop admission, keep decoding in-flight
        sequences up to the drain budget, then snapshot ALL undone work
        (queued + still-in-flight request specs) through the atomic
        checkpoint-commit helpers. Zero silently-lost requests: every
        submitted request either completed or is in the snapshot."""
        if snapshot_dir is None:
            snapshot_dir = self.config.drain_dir
        budget = (self.config.drain_budget_s if budget_s is None
                  else float(budget_s))
        self._draining = True            # submit() now sheds
        sched = self.scheduler
        completed_before = sched.stats["completed"]
        deadline = self.clock() + max(0.0, budget)
        while sched.active() and self.clock() < deadline:
            try:
                self.step(admit=False)
            except DecodeWatchdogError:
                break                    # hung chip: snapshot what's left
        # honour latched cancels/expiries before snapshotting: a request
        # the client disconnected from must end "cancelled", never be
        # resurrected on the successor engine as "drained" work
        sched.sweep_active()
        sched.honour_queued_cancels()
        specs = [request_spec(st) for _, st in sched.active()]
        specs += [request_spec(st) for st in sched.waiting]
        if specs and snapshot_dir is None:
            self._draining = False
            raise ValueError(
                f"drain: {len(specs)} request(s) still pending but no "
                "snapshot_dir is configured — refusing to discard work "
                "(pass snapshot_dir or ServingConfig.drain_dir)")
        path = None
        if specs:
            path = save_drain_snapshot(snapshot_dir, specs)
        for _, st in list(sched.active()):
            sched.drain_release(st)
        for st in list(sched.waiting):
            sched.drain_release(st)
        completed = sched.stats["completed"] - completed_before
        self._flight_event("drained", completed=completed,
                           snapshotted=len(specs), path=path)
        self._drained = True
        self._publish_gauges()
        return DrainReport(completed=completed, snapshotted=len(specs),
                           path=path)

    # -- live weight hot-swap (ISSUE 20) -------------------------------------
    def _swaps_counter(self):
        return get_registry().counter(
            "serve_swaps_total",
            "weight hot-swap lifecycle events (staged/cutover/refused/"
            "rolled_back/committed/drain_fallback)")

    def swap_weights(self, manifest_dir: str, mode: str = "auto") -> dict:
        """Load + verify a candidate checkpoint and swap it in WITHOUT
        dropping traffic (ISSUE 20).

        The candidate must be a committed manifest checkpoint of this
        engine's exact param tree (names/shapes/dtypes). A torn or
        mismatched push REFUSES (:class:`WeightSwapError`) with no side
        effects — the old weights keep serving. A valid push is staged
        beside the live tree and cut over atomically at the next
        iteration boundary (immediately when nothing is in flight);
        in-flight slots finish on the weights that wrote their KV pages
        (per-slot generation epoch — the LoRA pool-row convention
        generalized to the dense tree). When device memory can't hold
        two trees (``monitor.memory`` preflight), falls back to
        drain-and-restore through the PR 8 snapshot machinery: the tree
        swaps with nothing in flight and every unfinished continuation
        resubmits with its client callbacks re-attached.

        ``mode``: ``"auto"`` (preflight chooses) | ``"staged"`` |
        ``"drain"``. Returns a dict with ``mode``/``epoch`` plus
        per-mode detail. Weight swap never skips checkpoint
        verification: ``FLAGS_checkpoint_verify`` escalates the level
        but ``off`` does not disarm it."""
        if not self._hot_swap:
            raise RuntimeError(
                "FLAGS_serve_hot_swap is off — live weight swap is "
                "disarmed for this engine (the flag is read once at "
                "construction)")
        if mode not in ("auto", "staged", "drain"):
            raise ValueError(
                f"swap mode {mode!r}: expected auto|staged|drain")
        from ..core.flags import get_flag
        from ..distributed import checkpoint as ckpt
        state = None
        if chaos.active() and chaos.probe("serve.swap.torn_manifest"):
            reason = ("chaos serve.swap.torn_manifest: candidate "
                      "manifest torn mid-push")
        else:
            level = get_flag("checkpoint_verify")
            reason = ckpt.verify_checkpoint(
                manifest_dir,
                level="manifest" if level == "off" else level)
        if reason is None:
            try:
                state = ckpt.load(manifest_dir)
            except Exception as e:
                reason = f"load failed ({type(e).__name__}: {e})"
        if reason is None:
            reason = self._validate_candidate(state)
        if reason is not None:
            # refusal is side-effect free: old weights keep serving
            self._swap_stats["refused"] += 1
            self._swaps_counter().inc(event="refused")
            self._flight_event("swap_refused", manifest=manifest_dir,
                               reason=reason)
            raise WeightSwapError(manifest_dir, reason)
        # place each candidate leaf exactly like its live counterpart —
        # the compiled programs' input shardings must match untouched
        tree = {name: jax.device_put(jnp.asarray(state[name]),
                                     live.sharding)
                for name, live in self.params.items()}
        if chaos.active() and chaos.probe("serve.swap.bad_weights"):
            # corruption that SURVIVES manifest verification: plant NaN
            # into the first floating leaf. The swap path deliberately
            # does not scan finiteness (a full-tree reduction per push);
            # the damage manifests as non-finite logits in flight — the
            # signal the lifecycle controller's auto-rollback drills on.
            for name, leaf in tree.items():
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    tree[name] = jnp.full_like(leaf, float("nan"))
                    break
        if mode == "auto":
            mode = "staged" if self._swap_headroom_ok(tree) else "drain"
        if mode == "drain":
            return self._swap_via_drain(tree, manifest_dir)
        return self._stage(tree, manifest_dir)

    def _validate_candidate(self, state) -> Optional[str]:
        """None when ``state`` is exactly this model's param tree
        (names/shapes/dtypes), else the human-readable refusal reason."""
        if not isinstance(state, dict):
            return ("candidate is not a param dict "
                    f"({type(state).__name__})")
        live, cand = set(self.params), set(state)
        if live != cand:
            missing = sorted(live - cand)[:3]
            extra = sorted(cand - live)[:3]
            return ("param tree mismatch"
                    + (f"; missing {missing}" if missing else "")
                    + (f"; unexpected {extra}" if extra else ""))
        for name, ref in self.params.items():
            arr = state[name]
            if tuple(arr.shape) != tuple(ref.shape):
                return (f"shape mismatch at {name}: candidate "
                        f"{tuple(arr.shape)} vs serving "
                        f"{tuple(ref.shape)}")
            if jnp.dtype(arr.dtype) != jnp.dtype(ref.dtype):
                return (f"dtype mismatch at {name}: candidate "
                        f"{jnp.dtype(arr.dtype).name} vs serving "
                        f"{jnp.dtype(ref.dtype).name}")
        return None

    def _swap_headroom_ok(self, tree: dict) -> bool:
        """``monitor.memory`` preflight for the staged (dual-tree) swap:
        True when the device reports room for the candidate's bytes
        with a 25% safety margin (conservative: compares the WHOLE
        tree's bytes against one device's headroom, so sharded trees
        pass early). Backends that publish no allocator stats (the CPU
        test backend) stage — the host heap is the constraint there,
        not HBM."""
        from ..monitor import memory as _memory
        stats = _memory.device_memory_stats()
        if not stats:
            return True
        limit = stats.get("bytes_limit") \
            or stats.get("bytes_reservable_limit")
        if not limit:
            return True
        need = sum(int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
                   for a in tree.values())
        free = int(limit) - int(stats.get("bytes_in_use", 0))
        return free >= need * 1.25

    def _stage(self, tree: dict, manifest_dir: Optional[str]) -> dict:
        self._staged = {"params": tree, "manifest": manifest_dir}
        self._swap_stats["staged"] += 1
        self._swaps_counter().inc(event="staged")
        self._flight_event("weights_staged", manifest=manifest_dir,
                           epoch=self._weights_epoch + 1)
        if not self.scheduler.active():
            # nothing in flight: between steps IS an iteration boundary
            self._cutover()
        return {"mode": "staged", "epoch": self._weights_epoch,
                "pending": self._staged is not None}

    def _swap_via_drain(self, tree: dict,
                        manifest_dir: Optional[str]) -> dict:
        """HBM-constrained fallback: snapshot in-flight work as drain
        specs, release the slots (nothing references the old tree any
        more), cut over, then resubmit every unfinished continuation
        with its client callbacks re-attached — tokens already streamed
        stand, the continuation decodes on the new weights. Accounting
        records the interrupted residencies ``drained`` and the
        continuations as fresh submits, so the terminal-outcome
        identity still balances. The waiting queue is untouched: queued
        work never touched the old weights."""
        sched = self.scheduler
        sched.sweep_active()
        inflight = [(st, request_spec(st)) for _, st in sched.active()]
        for _, st in list(sched.active()):
            sched.drain_release(st)
        self._swap_stats["drain_swaps"] += 1
        self._swaps_counter().inc(event="drain_fallback")
        self._stage(tree, manifest_dir)   # no actives left: cuts over
        resubmitted = []
        for st_old, spec in inflight:
            reqs = requests_from_snapshot([spec])
            if not reqs:
                continue                  # already had its full budget
            req = reqs[0]
            req.on_token = st_old.request.on_token
            req.stop = st_old.request.stop
            resubmitted.append(self.submit(req))
        self._flight_event("weights_drain_swap",
                           resubmitted=len(resubmitted),
                           manifest=manifest_dir)
        return {"mode": "drain", "epoch": self._weights_epoch,
                "resubmitted": len(resubmitted),
                "states": resubmitted}

    def _cutover(self) -> None:
        """The atomic swap point (top of :meth:`step`, or immediately
        when idle): the staged tree becomes the live one. Slots in
        flight keep a reference to the tree that wrote their KV pages
        (``_retired``) until they terminate; the radix prefix tree is
        flushed — and donation detached for the transition — because
        cached pages carry the OLD weights' KV and must never seed a
        new-epoch admission."""
        staged, self._staged = self._staged, None
        old_epoch = self._weights_epoch
        actives = [st for _, st in self.scheduler.active()]
        for st in actives:
            if st.weights_epoch is None:
                # admitted before the boundary (possibly prefix-seeded
                # from old-weight pages): it belongs to the old epoch
                st.weights_epoch = old_epoch
        if actives:
            self._retired[old_epoch] = self.params
        self._previous = {"params": self.params,
                          "manifest": self._live_manifest}
        self._weights_epoch = old_epoch + 1
        self.params = staged["params"]
        self._live_manifest = staged["manifest"]
        if self.prefix_cache is not None:
            # safe with live shared-page references: the allocator is
            # refcounted, clear() just drops the tree's own refs
            self.prefix_cache.clear()
            if actives:
                # terminating old-epoch slots would DONATE old-weight
                # pages into the fresh tree: detach until they're gone
                # (free_slot skips donation while cache.prefix_cache
                # is None); _retire_unreferenced re-attaches
                self.cache.prefix_cache = None
        self._swap_stats["cutover"] += 1
        self._swaps_counter().inc(event="cutover")
        get_registry().gauge(
            "serve_weights_epoch",
            "live weights generation (increments at every hot-swap "
            "cutover, including rollback cutovers)").set(
                float(self._weights_epoch))
        self._flight_event("weights_cutover",
                           epoch=self._weights_epoch,
                           manifest=staged["manifest"],
                           in_flight_old_epoch=len(actives))

    def rollback_weights(self) -> dict:
        """Swap BACK to the pre-swap weights (the auto-rollback path).
        The previous tree is kept resident from cutover until
        :meth:`commit_swap`, so rollback needs no reload — it stages
        the retained tree and cuts over at the next iteration boundary
        (immediately when idle). After the rollback cutover the BAD
        tree becomes the retained previous; ``commit_swap()`` then
        drops it."""
        if not self._hot_swap:
            raise RuntimeError(
                "FLAGS_serve_hot_swap is off — rollback_weights is "
                "disarmed for this engine")
        prev = self._previous
        if prev is None:
            raise WeightSwapError(
                "<previous>", "no previous weights retained (already "
                "committed, or never swapped)")
        self._previous = None
        self._swap_stats["rolled_back"] += 1
        self._swaps_counter().inc(event="rolled_back")
        self._flight_event("weights_rolled_back",
                           from_epoch=self._weights_epoch,
                           to_manifest=prev["manifest"])
        return self._stage(prev["params"], prev["manifest"])

    def commit_swap(self) -> None:
        """Promotion: drop the retained pre-swap tree (the rollback
        anchor), freeing its memory. ``rollback_weights`` afterwards
        raises — the lifecycle controller calls this once the bake
        window passes (or after a rollback cutover, to drop the bad
        tree)."""
        if self._previous is not None:
            self._previous = None
            self._swap_stats["committed"] += 1
            self._swaps_counter().inc(event="committed")
            self._flight_event("weights_committed",
                               epoch=self._weights_epoch)

    def _params_for(self, epoch: Optional[int]):
        """The param tree for a slot epoch: the live tree for the live
        epoch (and for unstamped slots), a retired tree during a swap
        transition."""
        if epoch is None or epoch == self._weights_epoch:
            return self.params
        return self._retired[epoch]

    def _epoch_batches(self, pairs):
        """Partition this iteration's decodable slots into one
        (param_tree, pairs) dispatch batch per weights epoch. Outside a
        swap transition — the steady state, and always when
        ``FLAGS_serve_hot_swap`` is off — ``_retired`` is empty and
        this is ONE batch with the live tree: dispatch count and
        arguments identical to the pre-lifecycle engine (the flags-off
        pin)."""
        if not self._retired:
            return [(self.params, pairs)] if pairs else []
        by_epoch: Dict[int, list] = {}
        for slot, st in pairs:
            e = st.weights_epoch
            e = self._weights_epoch if e is None else e
            by_epoch.setdefault(e, []).append((slot, st))
        return [(self._params_for(e), by_epoch[e])
                for e in sorted(by_epoch)]

    def _retire_unreferenced(self) -> None:
        """Free retired trees no in-flight slot references any more;
        when the last one goes, the swap transition is over and prefix
        donation re-attaches (onto the flushed, new-epoch-only tree)."""
        live = {st.weights_epoch for _, st in self.scheduler.active()}
        for e in [e for e in self._retired if e not in live]:
            del self._retired[e]
            self._flight_event("weights_retired", epoch=e)
        if not self._retired and self.prefix_cache is not None \
                and self.cache.prefix_cache is None:
            self.cache.prefix_cache = self.prefix_cache

    # -- the serving iteration ----------------------------------------------
    def step(self, admit: bool = True) -> bool:
        """One scheduler iteration: honour drain/cancel/deadlines at the
        boundary, admit+prefill, then one decode dispatch over every
        active slot. Returns has_work. Raises :class:`EngineDrained`
        when a latched drain signal was honoured this step."""
        if self._drain_latch is not None and self._drain_latch.triggered \
                and not self._draining:
            raise EngineDrained(self.drain())
        if self._staged is not None:
            # the atomic cutover point: an iteration boundary, before
            # any admission/prefill/decode of this step
            self._cutover()
        sched = self.scheduler
        # iteration-boundary sweeps: queued expiries never touch a slot;
        # latched cancels / in-flight expiries free pages immediately.
        # Both are O(0) when no deadline/cancel exists — and never write
        # the registry except on an actual lifecycle event.
        sched.expire_queued()
        sched.sweep_active()
        if self._overload is not None:
            oldest_t = sched.oldest_waiting_t()
            delay = (self.clock() - oldest_t
                     if oldest_t is not None else 0.0)
            transition = self._overload.observe(delay)
            if transition is not None:
                self._overload_transition(transition)
        if admit:
            sched.plan_admissions()
        # ONE prefill pass per iteration over every prefilling slot —
        # newly admitted ones AND chunked prefills carried from earlier
        # iterations (they advance even under admit=False: a draining
        # engine must finish admitted work). With chunking off and no
        # prefix cache this reproduces the pre-ISSUE-15 groups exactly.
        groups = self._plan_prefill_groups()
        for gi, group in enumerate(groups):
            try:
                self._run_prefill(group)
            except DecodeWatchdogError:
                # every not-yet-prefilled state of this plan — the
                # tripped group AND any planned after it — holds a
                # slot but produced no token; un-admit them all in
                # one batch (admission order restored: groups are
                # bucketed by length, not arrival) or the retried
                # step() would decode slots with nothing to feed.
                # A mid-chunk state loses its chunk progress and
                # re-prefills from the queue — token-exact.
                pending = [st for g in groups[gi:] for st in g.states]
                pending.sort(key=lambda st: (st.admitted_t,
                                             st.request.request_id))
                sched.rollback_admission(pending)
                for st in pending:
                    self._trace_requeue(st, "watchdog_rollback")
                raise
        if self._decodable():
            if self._spec_k > 0:
                # drafts staged BEFORE the capacity pass so the verify
                # window's K/V writes land in real pages, never scratch
                self._stage_drafts()
            for st in sched.ensure_decode_capacity():
                # recompute-preemption: back to the queue with the SAME
                # trace — the span tree shows the second residency
                self._trace_requeue(st, "preemption")
            # one decode/verify dispatch per live weights epoch: a
            # single batch (the live tree) outside a swap transition
            for params, pairs in self._epoch_batches(self._decodable()):
                if any(st.draft for _, st in pairs):
                    self._run_verify(pairs, params)
                else:
                    self._run_decode(pairs, params)
        if self._retired:
            self._retire_unreferenced()
        self._publish_gauges()
        return sched.has_work

    def _decodable(self) -> List[Tuple[int, RequestState]]:
        """Active slots that take a decode/verify row this iteration —
        chunked prefills still mid-prompt do not."""
        return [(slot, st) for slot, st in self.scheduler.active()
                if not st.prefilling]

    def _trace_requeue(self, st: RequestState, reason: str) -> None:
        """A request lost its slot but lives on (recompute-preemption,
        watchdog rollback): close the open admitted span and open a new
        queued one — the trace context SURVIVES, same trace_id."""
        tr = st.trace
        if tr is None:
            return
        now = self.clock()
        spn = st.trace_spans.pop("admitted", None)
        if spn is not None:
            tr.end_span(spn, t=now, requeued=reason)
        # a never-prefilled state (watchdog rollback of a later group)
        # still holds its ORIGINAL open queued span — close it, or the
        # overwrite below would leak it open forever
        old_q = st.trace_spans.pop("queued", None)
        if old_q is not None:
            tr.end_span(old_q, t=now, requeued=reason)
        st.trace_spans["queued"] = tr.start_span(
            "queued", t=now, reason=reason,
            preemptions=st.preemptions)

    def _overload_transition(self, transition: str) -> None:
        reg = get_registry()
        on = transition == "enter"
        reg.gauge("serve_overload",
                  "1 while the queue-delay overload detector is "
                  "tripped (new submits are shed)").set(float(on))
        reg.counter("serve_overload_transitions_total",
                    "overload detector state changes").inc(
            state=transition)
        self._flight_event("overload", state=transition,
                           ewma_s=round(self._overload.ewma_s, 4),
                           threshold_s=self._overload.threshold_s,
                           queue_depth=self.scheduler.queue_depth)

    def _guarded_dispatch(self, kind: str, prog, args,
                          hang: bool = False):
        """Run one serving dispatch under the wall-clock watchdog
        (``FLAGS_serve_watchdog_s``; modeled on the eager-collective
        watchdog). Flag unset and no chaos hang = direct call, zero
        overhead. On a trip the hung thread is abandoned and the caller
        gets a structured :class:`DecodeWatchdogError` plus a
        flight-recorder dump — never a silent stall."""
        from ..core.flags import get_flag
        timeout_s = float(get_flag("serve_watchdog_s") or 0.0)
        if timeout_s <= 0.0 and not hang:
            return prog(*args)
        if hang and timeout_s <= 0.0:
            raise RuntimeError(
                "chaos site 'serve.decode.hang' fired but "
                "FLAGS_serve_watchdog_s is unset — set a watchdog "
                "budget so the hang can be converted into "
                "DecodeWatchdogError (the path this site exercises)")

        def job():
            if hang:
                # host-side hang BEFORE the dispatch: the program
                # never runs, so a post-trip retry of the step is
                # safe (same positions, same K/V writes)
                chaos.hang_loop(max(timeout_s, 1.0) * 20 + 60.0)
            return prog(*args)

        # one long-lived dispatcher thread serves every guarded
        # dispatch; only a trip abandons it (stuck in the hung
        # program) and costs the next dispatch a fresh worker
        worker = self._watchdog_worker
        if worker is None or not worker.usable:
            worker = DispatchWorker()
            self._watchdog_worker = worker
            self._watchdog_threads = [x for x in self._watchdog_threads
                                      if x.is_alive()]
            self._watchdog_threads.append(worker.thread)
        result = worker.dispatch(job, timeout_s)
        if result is None:
            # /readyz reports the trip until a later guarded dispatch
            # succeeds — a replica whose chip is hanging must drop out
            # of the load balancer, not keep absorbing traffic
            self._watchdog_tripped = {
                "kind": kind, "timeout_s": timeout_s,
                "dispatch": self._dispatch_seq}
            n_active = len(self.scheduler.active())
            for _, st in self.scheduler.active():
                # tail-based sampling: every request aboard a tripped
                # dispatch is retained with its full span tree
                if st.trace is not None:
                    st.trace.mark_anomaly("watchdog",
                                          watchdog_kind=kind)
            # retry soundness: a donating program hands the live pools
            # to the abandoned dispatch (invalidated on its thread, or
            # mutated in place by a late zombie finish) — only a
            # non-donating program leaves the engine state untouched
            retry_safe = not getattr(prog, "donate_argnums", ())
            get_registry().counter(
                "serve_watchdog_trips_total",
                "serving dispatch watchdog trips").inc(kind=kind)
            self._flight_event("decode_watchdog", kind=kind,
                               timeout_s=timeout_s,
                               dispatch=self._dispatch_seq,
                               active_slots=n_active,
                               retry_safe=retry_safe)
            if self._flight_enabled():
                try:
                    _flight.trip_dump(step=self._dispatch_seq,
                                      reason="serve_watchdog",
                                      kind=kind, timeout_s=timeout_s)
                except Exception:
                    pass          # forensics must not mask the trip
            raise DecodeWatchdogError(kind, timeout_s,
                                      self._dispatch_seq, n_active,
                                      retry_safe=retry_safe)
        self._watchdog_tripped = None      # guarded dispatch returned:
        if "error" in result:              # the chip answers again
            raise result["error"]
        return result["value"]

    def _sampling_arrays(self, states: Sequence[Optional[RequestState]]):
        n = len(states)
        temps = np.ones((n,), np.float32)
        tks = np.zeros((n,), np.int32)
        tps = np.ones((n,), np.float32)
        for i, st in enumerate(states):
            if st is None:
                continue
            s = st.request.sampling
            temps[i], tks[i], tps[i] = s.temperature, s.top_k, s.top_p
        return jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps)

    def _plan_prefill_groups(self) -> List[AdmissionGroup]:
        """Group every prefilling slot's NEXT chunk into bucketed
        dispatches. Chunking off + no prefix cache ⇒ every prefilling
        state is freshly admitted with its whole effective prompt as
        the one chunk — the exact pre-ISSUE-15 grouping (same buckets,
        same dispatch count, byte-identical traffic). Chunk length is
        ``min(FLAGS_serve_prefill_chunk, remaining)``; groups are keyed
        by (needs-context, length bucket) because a chunk at pos > 0
        must run the context program while pos == 0 chunks keep the
        bit-compatible plain one."""
        by_key: Dict[Tuple[int, bool, int], List[RequestState]] = {}
        for _, st in self.scheduler.active():
            if not st.prefilling:
                continue
            remaining = st.prefill_len - st.prefill_pos
            clen = min(self._chunk, remaining) if self._chunk > 0 \
                else remaining
            # keyed by weights epoch too (ISSUE 20): a mid-chunk prefill
            # carried across a cutover must keep its own tree, so it
            # can't share a dispatch with new-epoch admissions. The
            # epoch is constant outside a swap transition — identical
            # grouping and ordering to the pre-lifecycle planner.
            ep = st.weights_epoch
            key = (self._weights_epoch if ep is None else ep,
                   st.prefill_pos > 0, self.buckets.len_bucket(clen))
            by_key.setdefault(key, []).append(st)
        groups: List[AdmissionGroup] = []
        for ep, ctx, lb in sorted(by_key):
            sts = sorted(by_key[(ep, ctx, lb)],
                         key=lambda s: (s.admitted_t,
                                        s.request.request_id))
            mb = self.buckets.max_batch
            for i in range(0, len(sts), mb):
                chunk = sts[i:i + mb]
                groups.append(AdmissionGroup(
                    lb, self.buckets.batch_bucket(len(chunk)), chunk))
        return groups

    def _run_prefill(self, group: AdmissionGroup) -> None:
        nb, sp = group.batch_bucket, group.len_bucket
        states: List[Optional[RequestState]] = list(group.states)
        states += [None] * (nb - len(states))
        ids = np.zeros((nb, sp), np.int32)
        lens = np.ones((nb,), np.int32)
        pos = np.zeros((nb,), np.int32)
        ctx = any(st is not None and st.prefill_pos > 0
                  for st in states)
        chunked = False
        # padded rows map to None -> an all-scratch table row (their
        # K/V writes must never land in a live slot's pages)
        rows: List[Optional[int]] = [None] * nb
        for i, st in enumerate(states):
            if st is None:
                continue
            eff = st.effective_prompt()
            remaining = st.prefill_len - st.prefill_pos
            clen = min(self._chunk, remaining) if self._chunk > 0 \
                else remaining
            chunked = chunked or clen < remaining
            # COW contract: writes start at prefill_pos, which is never
            # below the shared-prefix coverage — a shared page is
            # read-only for this slot by construction
            assert st.prefill_pos >= (
                self.cache.slot_shared_blocks(st.slot)
                * self.cache.block_size)
            ids[i, :clen] = eff[st.prefill_pos:st.prefill_pos + clen]
            lens[i] = clen
            pos[i] = st.prefill_pos
            rows[i] = st.slot
        t0 = self.clock()
        if self._t_first_work is None:
            self._t_first_work = t0
        # stamp each residency's weights epoch at its FIRST chunk: the
        # KV this dispatch writes belongs to that tree, and every later
        # chunk/decode of the residency must keep using it across a hot
        # swap (groups are epoch-homogeneous by construction)
        for st in group.states:
            if st.weights_epoch is None:
                st.weights_epoch = self._weights_epoch
        params = self._params_for(group.states[0].weights_epoch)
        for st in group.states:
            tr = st.trace
            if tr is not None and "admitted" not in st.trace_spans:
                # queued ends / admitted opens at the scheduler's
                # admission stamp, not dispatch time — queueing delay
                # and prefill wait attribute to the right spans (a
                # chunked prefill opens them at its FIRST chunk only)
                qs = st.trace_spans.pop("queued", None)
                if qs is not None:
                    tr.end_span(qs, t=st.admitted_t)
                st.trace_spans["admitted"] = tr.start_span(
                    "admitted", t=st.admitted_t, slot=st.slot,
                    prefix_hit_tokens=st.prefill_pos)
        if ctx:
            prog = self._get_prefill_ctx(nb, sp)
            args = (params, self.cache.k, self.cache.v,
                    self.cache.table_array(rows), jnp.asarray(ids),
                    jnp.asarray(lens), jnp.asarray(pos),
                    self._next_key())
        else:
            prog = self._get_prefill(nb, sp)
            args = (params, self.cache.k, self.cache.v,
                    self.cache.table_array(rows), jnp.asarray(ids),
                    jnp.asarray(lens), self._next_key())
        temps, tks, tps = self._sampling_arrays(states)
        # a DecodeWatchdogError here propagates to step(), which rolls
        # back every not-yet-prefilled state of the plan (token-exact:
        # the tripped dispatch's pool writes died with its thread)
        toks, ok, new_k, new_v = self._guarded_dispatch(
            "prefill", prog,
            args + (temps, tks, tps, self._poison_array(states))
            + self._lora_args(states))
        self.cache.update(new_k, new_v)
        toks = np.asarray(toks)
        ok = np.asarray(ok)
        now = self.clock()
        self._stats["prefill_dispatches"] += 1
        if chunked or self._chunk > 0:
            self._stats["prefill_chunks"] += len(group.states)
            get_registry().counter(
                "serve_prefill_chunks_total",
                "chunked-prefill chunk rows dispatched"
            ).inc(len(group.states))
        reg = get_registry()
        reg.histogram("serve_prefill_seconds",
                      "prefill dispatch wall time").observe(
            now - t0, bucket=f"b{nb}_s{sp}")
        for i, st in enumerate(states):
            if st is None:
                continue
            clen = int(lens[i])
            st.prefill_pos += clen
            self._stats["prefill_tokens"] += clen
            final = st.prefill_pos >= st.prefill_len
            tr = st.trace
            if tr is not None:
                tr.end_span(tr.start_span(
                    "prefill", parent=st.trace_spans.get("admitted"),
                    t=t0, bucket=f"b{nb}_s{sp}", pos=int(pos[i]),
                    tokens=clen), t=now)
            if not ok[i]:
                self.scheduler.fail(st, "non-finite logits at prefill")
                continue
            if final:
                self._accept_token(st, int(toks[i]), now)

    def _poison_array(self, states: Sequence[Optional[RequestState]]):
        """[n] f32 additive logits poison: all zeros (bit-transparent)
        unless chaos marked a request, whose row turns NaN."""
        poison = np.zeros((len(states),), np.float32)
        for i, st in enumerate(states):
            if st is not None and st.poisoned:
                poison[i] = np.nan
        return jnp.asarray(poison)

    def _decode_table(self, per_slot: Sequence[Optional[RequestState]]):
        """Block-table argument for a decode/verify dispatch: only the
        DECODABLE slots' real rows; every other row — inactive slots
        AND mid-chunk prefilling slots, which hold live (possibly
        COW-shared) pages but take no decode row — is all-scratch, so
        the dispatch's unconditional per-row K/V scatter (pos 0, token
        0 for masked rows) can never land in a resident page. Without
        chunked prefill every resident slot is decodable and this is
        exactly ``table_array()`` (bit-identical args)."""
        return self.cache.table_array(
            [st.slot if st is not None else None for st in per_slot])

    def _stage_drafts(self) -> None:
        """Prompt-lookup drafting (ISSUE 15): propose up to ``k`` draft
        tokens per decodable slot from its own history — greedy slots
        verify by argmax match, sampled slots by stochastic residual
        acceptance (ISSUE 16). Zero drafts everywhere ⇒ the iteration
        falls through to the plain decode program — the drafter costs
        nothing when traffic has no self-repetition."""
        from .spec_decode import propose_ngram
        proposed = 0
        for _, st in self._decodable():
            st.draft = []
            budget = min(self._spec_k, st.remaining_new_tokens() - 1)
            if budget <= 0:
                continue
            hist = np.concatenate([
                st.request.prompt,
                np.asarray(st.generated, np.int32)])
            st.draft = [int(t) for t in propose_ngram(
                hist, budget, max_ngram=self._spec_ngram)]
            proposed += len(st.draft)
        if proposed:
            self._stats["spec_proposed"] += proposed
            get_registry().counter(
                "serve_spec_proposed_total",
                "speculative draft tokens proposed").inc(proposed)

    def _run_verify(self, pairs, params) -> None:
        """ONE batched verify dispatch over the given decodable slots
        (one epoch's worth — all of them outside a swap transition):
        row 0 is each slot's plain decode step; rows 1..k score the
        staged drafts. The accepted prefix plus one bonus token commit
        (greedy-exact vs the non-speculative path); the rejected tail's
        pages roll back by block-table truncation."""
        B = self.config.max_batch_slots
        S = self._spec_k + 1
        pos = np.zeros((B,), np.int32)
        ids = np.zeros((B, S), np.int32)
        active = np.zeros((B,), bool)
        per_slot: List[Optional[RequestState]] = [None] * B
        for slot, st in pairs:
            pos[slot] = st.seq_len - 1
            ids[slot, 0] = st.generated[-1]
            n = len(st.draft)
            if n:
                ids[slot, 1:1 + n] = st.draft
            active[slot] = True
            per_slot[slot] = st
        n_active = int(active.sum())
        t0 = self.clock()
        prog = self._get_verify()
        temps, tks, tps = self._sampling_arrays(per_slot)
        hang = chaos.active() and chaos.probe("serve.decode.hang")
        tok0, greedy, ok_rows, p_draft, tok_full, tok_resid, new_k, \
            new_v = self._guarded_dispatch(
                "verify", prog,
                (params, self.cache.k, self.cache.v,
                 self._decode_table(per_slot), jnp.asarray(pos),
                 jnp.asarray(ids), jnp.asarray(active), self._next_key(),
                 temps, tks, tps, self._poison_array(per_slot))
                + self._lora_args(per_slot),
                hang=hang)
        self.cache.update(new_k, new_v)
        tok0 = np.asarray(tok0)
        greedy = np.asarray(greedy)
        ok_rows = np.asarray(ok_rows)
        p_draft = np.asarray(p_draft)
        tok_full = np.asarray(tok_full)
        tok_resid = np.asarray(tok_resid)
        now = self.clock()
        dt = now - t0
        st_ = self._stats
        st_["decode_dispatches"] += 1
        st_["verify_dispatches"] += 1
        st_["decode_slot_steps"] += n_active
        st_["decode_batch_max"] = max(st_["decode_batch_max"], n_active)
        self._observe("decode_step", dt)
        reg = get_registry()
        reg.histogram("serve_decode_step_seconds",
                      "decode dispatch wall time (all slots)").observe(dt)
        reg.histogram("serve_decode_occupancy",
                      "active slots per decode dispatch",
                      buckets=tuple(range(1, B + 1))).observe(n_active)
        accepted = rolled_back = 0
        for slot, st in [(s, x) for s, x in enumerate(per_slot)
                         if x is not None]:
            n = len(st.draft)
            tr = st.trace
            if tr is not None:
                tr.end_span(tr.start_span(
                    f"verify[{len(st.generated)}]",
                    parent=st.trace_spans.get("admitted"), t=t0,
                    batch=n_active, proposed=n), t=now)
            if not ok_rows[slot, 0]:
                st.draft = []
                self.scheduler.fail(st, "non-finite logits at decode")
                continue
            sampled = st.request.sampling.temperature > 0.0
            if not sampled:
                # greedy acceptance: draft i survives iff it equals the
                # verifier's argmax at the previous row AND that row's
                # logits are finite (pad/garbage rows never commit)
                n_acc = 0
                while n_acc < n and ok_rows[slot, n_acc] \
                        and st.draft[n_acc] == int(greedy[slot, n_acc]):
                    n_acc += 1
                commit = [int(tok0[slot])] + \
                    [int(greedy[slot, i]) for i in range(1, n_acc + 1)
                     if ok_rows[slot, i]]
            else:
                # stochastic acceptance (ISSUE 16), point-mass drafter:
                # accept draft i with probability p_i(d_i) under row i's
                # filtered sampling distribution; on reject commit the
                # device's residual redraw (row i with d_i masked out)
                # and stop; on a clean sweep commit the bonus sample
                # from row n. Marginally identical to plain sampled
                # decode at every committed position.
                commit = []
                n_acc = 0
                for i in range(n):
                    if not ok_rows[slot, i]:
                        break
                    if self._spec_rng.random() < float(p_draft[slot, i]):
                        commit.append(int(st.draft[i]))
                        n_acc += 1
                    else:
                        commit.append(int(tok_resid[slot, i]))
                        break
                else:
                    if n == 0:
                        commit.append(int(tok0[slot]))
                    elif ok_rows[slot, n]:
                        commit.append(int(tok_full[slot, n]))
            committed = 0
            for t in commit:
                self._accept_token(st, t, now)
                committed += 1
                if st.terminal or st.is_done():
                    break
            acc = min(n_acc, committed) if sampled \
                else max(0, committed - 1)
            accepted += acc
            rolled_back += n - acc
            st.draft = []
            if not st.terminal:
                # block-table truncation: pages holding only the
                # rejected tail's K/V leave the table now (_accept_token
                # already finished any done request — its pages went
                # back wholesale through _terminate)
                self.cache.truncate_slot(st.slot, st.seq_len)
        if accepted:
            st_["spec_accepted"] += accepted
            reg.counter("serve_spec_accepted_total",
                        "speculative draft tokens accepted and "
                        "committed").inc(accepted)
        if rolled_back:
            st_["spec_rolled_back"] += rolled_back
            reg.counter("serve_spec_rolled_back_total",
                        "speculative draft tokens rejected and rolled "
                        "back by block-table truncation").inc(
                rolled_back)

    def _run_decode(self, pairs, params) -> None:
        B = self.config.max_batch_slots
        pos = np.zeros((B,), np.int32)
        tokens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        per_slot: List[Optional[RequestState]] = [None] * B
        for slot, st in pairs:
            # the newest generated token is not yet in the cache: this
            # step writes its K/V at position seq_len-1 and attends over
            # everything up to and including it
            pos[slot] = st.seq_len - 1
            tokens[slot] = st.generated[-1]
            active[slot] = True
            per_slot[slot] = st
        n_active = int(active.sum())
        t0 = self.clock()
        prog = self._get_decode()
        temps, tks, tps = self._sampling_arrays(per_slot)
        hang = chaos.active() and chaos.probe("serve.decode.hang")
        toks, ok, new_k, new_v = self._guarded_dispatch(
            "decode", prog,
            (params, self.cache.k, self.cache.v,
             self._decode_table(per_slot), jnp.asarray(pos),
             jnp.asarray(tokens), jnp.asarray(active), self._next_key(),
             temps, tks, tps, self._poison_array(per_slot))
            + self._lora_args(per_slot),
            hang=hang)
        self.cache.update(new_k, new_v)
        toks = np.asarray(toks)
        ok = np.asarray(ok)
        now = self.clock()
        dt = now - t0
        st_ = self._stats
        st_["decode_dispatches"] += 1
        st_["decode_slot_steps"] += n_active
        st_["decode_batch_max"] = max(st_["decode_batch_max"], n_active)
        self._observe("decode_step", dt)
        reg = get_registry()
        reg.histogram("serve_decode_step_seconds",
                      "decode dispatch wall time (all slots)").observe(dt)
        reg.histogram("serve_decode_occupancy",
                      "active slots per decode dispatch",
                      buckets=tuple(range(1, B + 1))).observe(n_active)
        for slot, st in list(pairs):
            tr = st.trace
            if tr is not None:
                # decode[i]: this request's share of the batched decode
                # dispatch that produced token i (i counts generated
                # tokens; prefill produced token 0)
                tr.end_span(tr.start_span(
                    f"decode[{len(st.generated)}]",
                    parent=st.trace_spans.get("admitted"), t=t0,
                    batch=n_active), t=now)
            if not ok[slot]:
                self.scheduler.fail(st, "non-finite logits at decode")
                continue
            self._accept_token(st, int(toks[slot]), now)

    def _accept_token(self, st: RequestState, token: int,
                      now: float) -> None:
        tr = st.trace
        # histogram exemplars: a latency bucket links to the concrete
        # trace that landed in it (None = no-op, the pre-trace path)
        ex = tr.trace_id if tr is not None else None
        first = st.first_token_t is None
        if first:
            st.first_token_t = now
            ttft = now - st.submitted_t
            self._observe("ttft", ttft)
            get_registry().histogram(
                "serve_ttft_seconds",
                "submit -> first token latency").observe(
                ttft, exemplar=ex)
        st.generated.append(token)
        self._stats["tokens_generated"] += 1
        self._t_last_token = now
        get_registry().counter(
            "serve_tokens_generated_total",
            "tokens sampled across all requests").inc()
        req = st.request
        det_sp = None
        if tr is not None and (req.on_token is not None
                               or req.stop is not None
                               or self.config.detokenizer is not None):
            det_sp = tr.start_span("detok", t=self.clock(),
                                   parent=st.trace_spans.get("admitted"))
        try:
            if chaos.active() and chaos.probe("serve.detok.raise"):
                raise chaos.ChaosFault("serve.detok.raise")
            if req.on_token is not None:
                text = None
                if self.config.detokenizer is not None:
                    text = self.config.detokenizer.piece(
                        token, is_first=len(st.generated) == 1)
                req.on_token(req, token, text)
            if req.stop is not None and req.stop(list(st.generated)):
                st.stop_hit = True
        except Exception as e:
            # fault isolation: a raising detokenizer / client callback /
            # malformed stop condition fails ONLY this request — the
            # rest of the batch streams on
            if det_sp is not None:
                tr.end_span(det_sp, t=self.clock(), error=repr(e))
            self.scheduler.fail(
                st, f"detokenizer/callback error: {e!r}")
            return
        if det_sp is not None:
            tr.end_span(det_sp, t=self.clock())
        if st.is_done():
            self.scheduler.finish(st)
            e2e = now - st.submitted_t
            self._observe("e2e", e2e)
            n = len(st.generated)
            if n > 1 and st.first_token_t is not None:
                tpot = (now - st.first_token_t) / (n - 1)
                self._observe("tpot", tpot)
                get_registry().histogram(
                    "serve_tpot_seconds",
                    "mean per-token decode latency per request"
                ).observe(tpot, exemplar=ex)
            reg = get_registry()
            reg.histogram("serve_e2e_seconds",
                          "submit -> completion latency").observe(
                e2e, exemplar=ex)
            if st.deadline_t is not None:
                slack = st.deadline_t - now
                reg.histogram(
                    "serve_deadline_slack_seconds",
                    "deadline minus completion time for deadline-"
                    "carrying requests (negative = finished late)",
                    buckets=self.DEADLINE_SLACK_BUCKETS).observe(
                    slack, exemplar=ex)
                if self._slo_deadline is not None:
                    self._slo_deadline.record(
                        good=1 if slack >= 0 else 0,
                        bad=0 if slack >= 0 else 1)
                    self._slo_deadline.publish()

    def _publish_gauges(self) -> None:
        reg = get_registry()
        reg.gauge("serve_queue_depth",
                  "requests waiting for a batch slot").set(
            self.scheduler.queue_depth)
        reg.gauge("serve_active_slots", "requests holding a batch slot"
                  ).set(len(self.scheduler.active()))
        reg.gauge("serve_kv_pages_in_use",
                  "allocated KV pages (of the shared pool)").set(
            self.cache.allocator.pages_in_use)
        if self.cache.quant:
            # emits-metrics: serve_kv_quant_bytes_per_token
            reg.gauge(
                "serve_kv_quant_bytes_per_token",
                "HBM bytes per cached token position under "
                "FLAGS_serve_kv_quant (int8 pages + f32 per-head "
                "scales)").set(float(self.cache.kv_bytes_per_token()))
        if self.scheduler.tenant_quota is not None:
            # delta-publish the per-tenant quota deferrals (prefix-
            # metrics convention: scheduler counts, engine publishes)
            for tenant, n in self.scheduler.tenant_deferrals.items():
                delta = n - self._quota_published.get(tenant, 0)
                if delta > 0:
                    # emits-metrics: serve_tenant_quota_deferrals_total
                    reg.counter(
                        "serve_tenant_quota_deferrals_total",
                        "admissions deferred by the per-tenant slot "
                        "quota").inc(delta, tenant=tenant)
                    self._quota_published[tenant] = n
        if self.prefix_cache is not None:
            self._publish_prefix_metrics(reg)

    def _publish_prefix_metrics(self, reg) -> None:
        """Delta-publish the prefix cache's host-side stats (the cache
        itself never touches the registry — recsys tier convention).
        Flag off ⇒ this is never called: zero new series."""
        pc = self.prefix_cache
        reg.gauge("serve_prefix_cached_pages",
                  "KV pages resident in the radix prefix cache").set(
            pc.cached_pages)
        for stat, name, help_ in (
                ("hits", "serve_prefix_hits_total",
                 "admissions that matched a cached prefix"),
                ("misses", "serve_prefix_misses_total",
                 "admissions with no cached prefix"),
                ("hit_tokens", "serve_prefix_hit_tokens_total",
                 "prompt tokens served from cached pages instead of "
                 "prefill"),
                ("evicted_pages", "serve_prefix_evicted_pages_total",
                 "cached pages evicted under allocation pressure")):
            delta = pc.stats[stat] - self._prefix_published.get(stat, 0)
            if delta > 0:
                # emits-metrics: serve_prefix_hits_total, serve_prefix_misses_total
                # emits-metrics: serve_prefix_hit_tokens_total, serve_prefix_evicted_pages_total
                reg.counter(name, help_).inc(delta)
                self._prefix_published[stat] = pc.stats[stat]

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        d = dict(self._stats)
        d.update(self.scheduler.stats)
        d["programs"] = dict(self._programs_info)
        d["resident_programs"] = len(self._programs)
        d["queue_depth"] = self.scheduler.queue_depth
        d["active_slots"] = len(self.scheduler.active())
        d["kv_pages_in_use"] = self.cache.allocator.pages_in_use
        return d

    def metrics_summary(self) -> dict:
        """Host-side latency/throughput summary (exact percentiles over
        the raw per-request samples — the BENCH_serve payload)."""

        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else None

        elapsed = None
        if self._t_first_work is not None and \
                self._t_last_token is not None:
            elapsed = max(self._t_last_token - self._t_first_work, 1e-9)
        lat = self._lat
        sstats = self.scheduler.stats
        return {
            "requests_completed": sstats["completed"],
            "requests_submitted": sstats["submitted"],
            "requests_expired": sstats["expired"],
            "requests_expired_queued": sstats["expired_queued"],
            "requests_shed": sstats["shed"],
            "requests_cancelled": sstats["cancelled"],
            "requests_failed": sstats["failed"],
            "requests_drained": sstats["drained"],
            "preemptions": self.scheduler.stats["preemptions"],
            "tokens_generated": self._stats["tokens_generated"],
            "elapsed_s": elapsed,
            "tokens_per_sec": (self._stats["tokens_generated"] / elapsed
                               if elapsed else None),
            "ttft_p50_s": pct(lat["ttft"], 50),
            "ttft_p99_s": pct(lat["ttft"], 99),
            "tpot_p50_s": pct(lat["tpot"], 50),
            "tpot_p99_s": pct(lat["tpot"], 99),
            "decode_step_p50_s": pct(lat["decode_step"], 50),
            "decode_step_p99_s": pct(lat["decode_step"], 99),
            "decode_dispatches": self._stats["decode_dispatches"],
            "mean_decode_occupancy": (
                self._stats["decode_slot_steps"]
                / self._stats["decode_dispatches"]
                if self._stats["decode_dispatches"] else None),
            "ttft_p99_s": pct(lat["ttft"], 99),
            "prefill_tokens": self._stats["prefill_tokens"],
            "prefill_chunks": self._stats["prefill_chunks"],
            "verify_dispatches": self._stats["verify_dispatches"],
            # prefix hit rate: share of prompt positions served from
            # cached pages instead of prefill compute
            "prefix_hit_pct": (
                100.0 * self.prefix_cache.stats["hit_tokens"]
                / max(1, self.prefix_cache.stats["hit_tokens"]
                      + self._stats["prefill_tokens"])
                if self.prefix_cache is not None else None),
            "prefix_hit_tokens": (
                self.prefix_cache.stats["hit_tokens"]
                if self.prefix_cache is not None else 0),
            # draft acceptance: committed draft tokens per proposed
            "spec_accept_pct": (
                100.0 * self._stats["spec_accepted"]
                / self._stats["spec_proposed"]
                if self._stats["spec_proposed"] else None),
            "spec_proposed": self._stats["spec_proposed"],
            "spec_accepted": self._stats["spec_accepted"],
            "spec_rolled_back": self._stats["spec_rolled_back"],
            # multi-tenant serving (ISSUE 17)
            "kv_bytes_per_token": self.cache.kv_bytes_per_token(),
            "kv_quant": self.cache.quant or None,
            "lora_adapters_loaded": (self.lora.num_loaded
                                     if self.lora is not None else 0),
            "lora_swaps": (self.lora.swaps
                           if self.lora is not None else 0),
            "quota_deferred": sstats.get("quota_deferred", 0),
            # model lifecycle (ISSUE 20)
            "weights_epoch": self._weights_epoch,
            "weight_swaps": self._swap_stats["cutover"],
            "weight_swaps_refused": self._swap_stats["refused"],
            "weight_swap_rollbacks": self._swap_stats["rolled_back"],
        }

    def shutdown(self) -> None:
        """Drop compiled programs, cache pools, the drain latch (signal
        handlers restored), admin-plane registrations and any live
        watchdog threads (test isolation / explicit teardown)."""
        self._detach_admin()
        if self._drain_latch is not None:
            self._drain_latch.close()
            self._drain_latch = None
        if self._watchdog_worker is not None:
            self._watchdog_worker.close()
            self._watchdog_worker = None
        if self._watchdog_threads:
            # a thread abandoned in a chaos hang exits as soon as the
            # hang is cancelled; one stuck in a real dispatch is daemon
            # and joins best-effort
            chaos.cancel_hangs()
            for t in self._watchdog_threads:
                t.join(timeout=0.5)
            self._watchdog_threads = []
            # this engine's teardown must not neutralize still-armed
            # hang sites for other live engines
            chaos.rearm_hangs()
        self._programs.clear()
        self.scheduler.waiting.clear()
        for slot, _ in list(self.scheduler.active()):
            self.cache.free_slot(slot)
            self.scheduler.slots[slot] = None
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
            self.cache.prefix_cache = None
            self.prefix_cache = None
        # unstage any half-loaded candidate tree and drop retained /
        # retired trees, clearing the epoch latch (ISSUE 20 fix): an
        # aborted swap must not leak a full param tree of device memory
        # into the next engine constructed in this process
        self._staged = None
        self._retired.clear()
        self._previous = None
        self.cache.k = self.cache.v = None
