"""Multi-tenant LoRA adapter management for the serving engine (ISSUE 17).

Thousands of fine-tunes over one base model is the multi-tenant serving
shape (Punica / S-LoRA): adapters are rank-r deltas on the fused QKV
projection, small enough that N of them fit beside the base weights, and
the bgmv kernel (``ops/pallas/bgmv.py``) applies a DIFFERENT adapter per
batch slot inside the one compiled decode/prefill/verify program — so
requests for different fine-tunes share a batch instead of a queue.

:class:`LoRAManager` owns the device-resident pools:

- stacked per-layer weights ``a [L, A, r, E]`` / ``b [L, A, r, 3*H*D]``
  where row ``A`` indexes the adapter. **Row 0 is the reserved ZERO
  adapter**: all-zero weights, so base-model requests ride the same
  program with a delta of exactly 0.0 — mixing adapted and plain
  requests costs nothing;
- a name -> row map plus per-adapter slot refcounts: admission acquires
  the adapter, slot release drops it, and :meth:`unload_adapter` refuses
  while any slot still references the adapter (no in-flight request can
  ever decode against freed or repurposed weights);
- hot-swap through the checkpoint manifest machinery
  (``distributed.checkpoint``): :meth:`load_adapter` with a ``path``
  verifies the committed manifest first and validates every shape
  BEFORE touching the pools — a torn or mismatched adapter checkpoint
  leaves the pools exactly as they were (atomic load).

The pools are ARGUMENTS of the compiled serving programs (like block
tables and positions), so loading or unloading an adapter between steps
never recompiles anything — the AOT-compile invariant of the engine.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["LoRAManager", "save_adapter_checkpoint"]


def save_adapter_checkpoint(path: str, lora_a, lora_b) -> None:
    """Commit adapter weights (``a [L, r, E]``, ``b [L, r, O]``) as a
    manifest-covered checkpoint dir :meth:`LoRAManager.load_adapter` can
    hot-swap in (synchronous: durable when the call returns)."""
    from ..distributed import checkpoint as ckpt
    ckpt.save({"lora_a": jnp.asarray(lora_a), "lora_b": jnp.asarray(lora_b)},
              path, asynchronous=False)
    ckpt.wait()


class LoRAManager:
    """Device adapter pools + host name/refcount bookkeeping.

    ``max_adapters`` is the number of LOADABLE adapters; the pools hold
    ``max_adapters + 1`` rows (row 0 = the zero adapter). ``out_features``
    is the fused-QKV output width ``3 * H * D``.
    """

    def __init__(self, num_layers: int, hidden_size: int,
                 out_features: int, *, max_adapters: int, rank: int,
                 dtype=jnp.float32):
        if max_adapters < 1:
            raise ValueError("max_adapters must be >= 1")
        if rank < 1:
            raise ValueError("rank must be >= 1")
        self.num_layers = int(num_layers)
        self.hidden_size = int(hidden_size)
        self.out_features = int(out_features)
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        rows = self.max_adapters + 1
        self.a = jnp.zeros((num_layers, rows, rank, hidden_size), dtype)
        self.b = jnp.zeros((num_layers, rows, rank, out_features), dtype)
        self._rows: Dict[str, int] = {}
        self._refs: Dict[str, int] = {}
        self._free: List[int] = list(range(1, rows))
        #: cumulative hot-swaps (loads), mirrored into
        #: serve_lora_swaps_total under monitor mode
        self.swaps = 0

    # -- introspection -------------------------------------------------------
    @property
    def num_loaded(self) -> int:
        return len(self._rows)

    def loaded(self) -> List[str]:
        return sorted(self._rows)

    def row(self, name: str) -> Optional[int]:
        """Pool row serving ``name``, or None when not loaded."""
        return self._rows.get(name)

    def refcount(self, name: str) -> int:
        return self._refs.get(name, 0)

    def pools(self) -> Tuple[object, object]:
        """The stacked ``(a, b)`` pools — serving-program arguments."""
        return self.a, self.b

    # -- lifecycle -----------------------------------------------------------
    def _validate(self, name: str, a, b):
        L, r = self.num_layers, self.rank
        want_a = (L, r, self.hidden_size)
        want_b = (L, r, self.out_features)
        if tuple(a.shape) != want_a or tuple(b.shape) != want_b:
            raise ValueError(
                f"adapter {name!r}: weights are a{tuple(a.shape)} / "
                f"b{tuple(b.shape)}, this manager serves a{want_a} / "
                f"b{want_b}")
        return a, b

    def load_adapter(self, name: str, weights=None,
                     path: Optional[str] = None) -> int:
        """Load (hot-swap in) an adapter and return its pool row.

        ``weights``: ``(a [L, r, E], b [L, r, O])`` arrays, or ``path``:
        a committed checkpoint dir written by
        :func:`save_adapter_checkpoint`. Everything is verified and
        shape-checked BEFORE the pools mutate, so a bad source leaves
        the manager unchanged. Loading an already-loaded name is a no-op
        (returns its existing row) — swap-in-place requires an explicit
        unload first, because in-flight requests may reference the row.
        """
        if not name:
            raise ValueError("adapter name must be non-empty")
        existing = self._rows.get(name)
        if existing is not None:
            return existing
        if (weights is None) == (path is None):
            raise ValueError("pass exactly one of weights= or path=")
        if path is not None:
            from ..distributed import checkpoint as ckpt
            reason = ckpt.verify_checkpoint(path, level="manifest")
            if reason is not None:
                raise ValueError(
                    f"adapter {name!r}: checkpoint {path} failed "
                    f"verification: {reason}")
            state = ckpt.load(path)
            try:
                a, b = state["lora_a"], state["lora_b"]
            except (KeyError, TypeError):
                raise ValueError(
                    f"adapter {name!r}: checkpoint {path} holds no "
                    "lora_a/lora_b entries")
        else:
            a, b = weights
        a = jnp.asarray(a, self.a.dtype)
        b = jnp.asarray(b, self.b.dtype)
        self._validate(name, a, b)
        if not self._free:
            raise RuntimeError(
                f"adapter pool full ({self.max_adapters} rows); unload "
                "an unreferenced adapter first")
        row = self._free.pop(0)
        self.a = self.a.at[:, row].set(a)
        self.b = self.b.at[:, row].set(b)
        self._rows[name] = row
        self._refs[name] = 0
        self.swaps += 1
        self._publish(swapped=True)
        return row

    def unload_adapter(self, name: str) -> None:
        """Refcounted unload: only an adapter no slot references may
        leave (its row is zeroed and returned to the free list). A
        referenced adapter raises — the caller retries after the
        referencing requests drain."""
        row = self._rows.get(name)
        if row is None:
            raise KeyError(f"adapter {name!r} is not loaded")
        refs = self._refs.get(name, 0)
        if refs > 0:
            raise RuntimeError(
                f"adapter {name!r} still referenced by {refs} slot(s); "
                "unload only when no slot references the adapter")
        del self._rows[name]
        self._refs.pop(name, None)
        # zero the row so a stale id could only ever select the zero
        # delta, never another tenant's weights
        self.a = self.a.at[:, row].set(0.0)
        self.b = self.b.at[:, row].set(0.0)
        self._free.append(row)
        self._publish()

    # -- slot references -----------------------------------------------------
    def acquire(self, name: str) -> int:
        """Admission-time reference: the slot now decodes against
        ``name``. Returns the pool row."""
        row = self._rows.get(name)
        if row is None:
            raise KeyError(f"adapter {name!r} is not loaded")
        self._refs[name] = self._refs.get(name, 0) + 1
        return row

    def release(self, name: str) -> None:
        """Drop a slot's reference (slot freed: finish, preemption,
        failure, drain)."""
        refs = self._refs.get(name, 0)
        if refs <= 0:
            raise RuntimeError(
                f"release of adapter {name!r} without a live reference")
        self._refs[name] = refs - 1

    def rows_for(self, names: Sequence[Optional[str]]):
        """Per-slot adapter rows for a dispatch: ``None`` (base-model
        request or empty slot) maps to the zero adapter, row 0."""
        return jnp.asarray(
            np.array([0 if n is None else self._rows[n] for n in names],
                     np.int32))

    def _publish(self, swapped: bool = False) -> None:
        from ..monitor import enabled as _mon_enabled
        if not _mon_enabled():
            return
        from ..monitor import get_registry
        reg = get_registry()
        if swapped:
            reg.counter(
                "serve_lora_swaps_total",
                "LoRA adapter hot-swaps (loads) into the serving "
                "pools").inc()
        reg.gauge(
            "serve_lora_adapters_loaded",
            "LoRA adapters currently resident in the serving pools "
            "(zero adapter excluded)").set(float(self.num_loaded))
