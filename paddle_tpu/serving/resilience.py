"""Serving resilience primitives: typed overload/drain/watchdog errors,
the queue-delay overload detector, the SIGTERM drain latch, and atomic
drain snapshots (ISSUE 8).

Production serving treats overload, cancellation and shutdown as
*states*, not exceptions-in-the-bad-sense: a shed request is an answer
("come back later"), a drain is a planned handoff, a hung decode step is
a structured incident with forensics. This module holds the pieces the
engine and scheduler compose:

- :class:`ServerOverloaded` — the typed admission-refusal error clients
  key retry/backoff behaviour on (reason: queue_full | overload |
  draining);
- :class:`OverloadDetector` — EWMA of head-of-queue delay with
  enter/exit hysteresis; while tripped the engine sheds every new
  submit, because admitting work it cannot start only converts future
  timeouts into queue memory;
- :class:`DrainLatch` — the PR 5 signal-latch pattern
  (``CheckpointManager._on_signal``): the handler only records the
  signal, the engine honours it at the next iteration boundary;
- :func:`save_drain_snapshot` / :func:`load_drain_snapshot` — undone
  work (queued + preempted request specs) committed through the
  checkpoint-manifest atomic-commit helpers
  (``distributed.checkpoint._commit``), so a torn write (chaos site
  ``ckpt.write.torn``) can never pass for a snapshot and a restarted
  engine falls back to the newest *valid* one;
- :class:`DecodeWatchdogError` — a decode dispatch that blew its
  ``FLAGS_serve_watchdog_s`` wall-clock budget, raised instead of a
  silent stall (modeled on ``FLAGS_collective_timeout_s``).
"""

from __future__ import annotations

import json
import logging
import os
import re
import signal as signal_mod
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ServerOverloaded", "EngineDrained", "DecodeWatchdogError",
           "OverloadDetector", "DrainLatch", "DrainReport",
           "request_spec", "save_drain_snapshot", "load_drain_snapshot",
           "requests_from_snapshot", "DRAIN_STATE_NAME"]

#: the one payload file of a drain snapshot directory (next to the
#: checkpoint manifest that commits it)
DRAIN_STATE_NAME = "drain_state.json"

_DRAIN_DIR_RE = re.compile(r"^drain_(\d+)$")


class ServerOverloaded(RuntimeError):
    """Admission refused: the engine is shedding load.

    ``reason`` is one of ``queue_full`` (bounded queue at capacity and
    the shedding policy produced no victim), ``overload`` (the
    queue-delay EWMA detector is tripped) or ``draining`` (the engine is
    shutting down gracefully). A client should back off and retry —
    the request was never admitted, nothing holds state for it."""

    def __init__(self, reason: str, queue_depth: Optional[int] = None,
                 ewma_s: Optional[float] = None,
                 threshold_s: Optional[float] = None):
        detail = {"queue_full": "request queue at capacity",
                  "overload": "queue-delay overload detector tripped",
                  "draining": "engine is draining"}.get(reason, reason)
        msg = f"server overloaded ({reason}): {detail}"
        if queue_depth is not None:
            msg += f"; queue_depth={queue_depth}"
        if ewma_s is not None:
            msg += f"; queue_delay_ewma={ewma_s:.3f}s"
        if threshold_s is not None:
            msg += f" (threshold {threshold_s:g}s)"
        super().__init__(msg)
        self.reason = reason
        self.queue_depth = queue_depth
        self.ewma_s = ewma_s
        self.threshold_s = threshold_s


class EngineDrained(Exception):
    """Raised by ``ServingEngine.step``/``run`` after a latched drain
    signal has been honoured (the serving analogue of PR 5's
    ``PreemptionSignal``): in-flight work finished or was snapshotted,
    nothing was silently lost. Carries the :class:`DrainReport`."""

    def __init__(self, report: "DrainReport"):
        super().__init__(
            f"engine drained: {report.completed} completed in the grace "
            f"period, {report.snapshotted} snapshotted"
            + (f" to {report.path}" if report.path else ""))
        self.report = report


class DecodeWatchdogError(RuntimeError):
    """A serving dispatch exceeded ``FLAGS_serve_watchdog_s``.

    The decode loop's analogue of :class:`CollectiveTimeoutError`: XLA
    cannot cancel an in-flight program from python, so the hung dispatch
    thread is abandoned and the caller gets a structured error (plus a
    flight-recorder dump when recording is on) instead of a controller
    that never returns."""

    def __init__(self, kind: str, timeout_s: float, dispatch_seq: int,
                 active_slots: int, retry_safe: bool = True):
        tail = (
            "retrying the step is token-exact for greedy requests "
            "(same positions, same K/V writes)." if retry_safe else
            "the program donates the KV pools (compiled before "
            "FLAGS_serve_watchdog_s was armed), so the abandoned "
            "dispatch owns them and the step CANNOT be retried — "
            "restart the engine, or arm the watchdog before the first "
            "dispatch so programs compile without donation.")
        super().__init__(
            f"serving {kind} dispatch #{dispatch_seq} did not return "
            f"within {timeout_s:g}s (FLAGS_serve_watchdog_s) with "
            f"{active_slots} active slot(s). The dispatch thread is "
            f"abandoned; {tail}")
        self.kind = kind
        self.timeout_s = timeout_s
        self.dispatch_seq = dispatch_seq
        self.active_slots = active_slots
        self.retry_safe = retry_safe


class DispatchWorker:
    """One long-lived thread serving every watchdog-guarded dispatch.

    With ``FLAGS_serve_watchdog_s`` armed, every decode step needs a
    thread the caller can time out on — but spawning one per dispatch
    puts thread creation/teardown on the per-token hot path. This worker
    is created once and fed jobs over a queue; only a TRIP costs a
    thread: the worker is stuck inside the hung program, so the engine
    abandons the whole worker and the next dispatch starts a fresh one
    (the abandoned thread exits on its own if the hang ever resolves —
    e.g. ``chaos.cancel_hangs()`` — instead of parking on the queue)."""

    def __init__(self):
        import queue
        import threading
        self._work: "queue.Queue" = queue.Queue()
        self._abandoned = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-watchdog-worker")
        self.thread.start()

    @property
    def usable(self) -> bool:
        return not self._abandoned and self.thread.is_alive()

    def _loop(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            fn, result, done = item
            try:
                result["value"] = fn()
            except BaseException as e:  # surfaces on the caller thread
                result["error"] = e
            finally:
                done.set()
            if self._abandoned:
                return

    def dispatch(self, fn, timeout_s: float) -> Optional[dict]:
        """Run ``fn`` on the worker thread; None = timed out (the worker
        is abandoned and must not be reused)."""
        import threading
        result: dict = {}
        done = threading.Event()
        self._work.put((fn, result, done))
        if not done.wait(timeout_s):
            self._abandoned = True
            return None
        return result

    def close(self) -> None:
        """Stop an idle worker (an abandoned one exits by itself)."""
        self._abandoned = True
        self._work.put(None)


@dataclass
class DrainReport:
    """What a drain did: requests finished inside the grace budget,
    requests snapshotted for a successor engine, and the committed
    snapshot path (None when nothing was pending)."""

    completed: int
    snapshotted: int
    path: Optional[str]


class OverloadDetector:
    """EWMA of head-of-queue delay with enter/exit hysteresis.

    Observed once per engine iteration with the age of the oldest
    waiting request (0 when the queue is empty) — unlike an
    admission-time sample this keeps rising while the queue is *stuck*,
    which is exactly the overload that matters. Trips at
    ``threshold_s``; recovers at ``threshold_s * exit_frac`` so the
    shedding state does not flap at the boundary."""

    def __init__(self, threshold_s: float, alpha: float = 0.3,
                 exit_frac: float = 0.5):
        if threshold_s <= 0:
            raise ValueError("overload threshold must be > 0")
        if not (0.0 < alpha <= 1.0):
            raise ValueError("EWMA alpha must be in (0, 1]")
        if not (0.0 < exit_frac < 1.0):
            # exit_frac >= 1 inverts the hysteresis: the detector would
            # flap enter/exit on every observe between the two bounds
            raise ValueError("overload exit_frac must be in (0, 1)")
        self.threshold_s = float(threshold_s)
        self.alpha = float(alpha)
        self.exit_s = float(threshold_s) * float(exit_frac)
        self.ewma_s = 0.0
        self.overloaded = False

    def observe(self, queue_delay_s: float) -> Optional[str]:
        """Fold one head-of-queue delay sample in; returns ``"enter"`` /
        ``"exit"`` on a state transition, else None."""
        self.ewma_s = (self.alpha * float(queue_delay_s)
                       + (1.0 - self.alpha) * self.ewma_s)
        if not self.overloaded and self.ewma_s > self.threshold_s:
            self.overloaded = True
            return "enter"
        if self.overloaded and self.ewma_s < self.exit_s:
            self.overloaded = False
            return "exit"
        return None


class DrainLatch:
    """Latch a shutdown signal; the engine honours it at the next
    iteration boundary (handlers must be async-signal-thin — the PR 5
    ``CheckpointManager`` rule). ``trigger()`` arms it programmatically
    (tests, ops tooling). ``close()`` restores the original handlers."""

    def __init__(self, signals=(signal_mod.SIGTERM,)):
        self._signum: Optional[int] = None
        self._old: Dict[int, object] = {}
        for sig in signals or ():
            try:
                self._old[sig] = signal_mod.signal(sig, self._on_signal)
            except (ValueError, OSError):
                # non-main thread / unsupported signal: programmatic
                # trigger() still works
                logger.warning("DrainLatch: cannot install handler for "
                               "signal %s", sig)

    def _on_signal(self, signum, frame):
        self._signum = signum

    @property
    def triggered(self) -> bool:
        return self._signum is not None

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def trigger(self) -> None:
        self._signum = -1

    def close(self) -> None:
        for sig, old in self._old.items():
            try:
                signal_mod.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old = {}


# ---------------------------------------------------------------------------
# drain snapshots
# ---------------------------------------------------------------------------


def request_spec(st) -> dict:
    """Serializable spec of a request's undone work. ``prompt`` is the
    ORIGINAL prompt; ``generated`` the tokens produced before the drain,
    so a restorer can either continue the stream (greedy continuation is
    token-exact — the recompute-preemption property) or replay from
    scratch. Callbacks (``on_token``/``stop``) do not serialize; the
    resubmitting client re-attaches its own."""
    req = st.request
    s = req.sampling
    spec = {
        "request_id": int(req.request_id),
        "prompt": [int(t) for t in np_tolist(req.prompt)],
        "generated": [int(t) for t in st.generated],
        "max_new_tokens": int(req.max_new_tokens),
        "sampling": {"temperature": float(s.temperature),
                     "top_k": int(s.top_k), "top_p": float(s.top_p)},
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "priority": int(getattr(req, "priority", 0)),
        # ISSUE 15: chunked-prefill progress and in-flight (uncommitted)
        # draft tokens at drain time. Neither changes what the successor
        # RECOMPUTES — `generated` holds only committed tokens, so
        # resuming from prompt+generated is token-exact whether the
        # drain landed mid-chunk or mid-verify — but recording them
        # keeps the snapshot an honest picture of undone work (the
        # torn-commit drill asserts both survive the round-trip).
        "prefill_pos": int(getattr(st, "prefill_pos", 0)),
        "draft": [int(t) for t in getattr(st, "draft", ())],
    }
    # trace-context survival: the successor engine resumes the SAME
    # trace_id (monitor/trace.py), so a drained request's span tree
    # continues instead of forking a new identity. The PARENT link and
    # process label survive too (ISSUE 18): a continuation restored
    # outside the router still parents under the original router span
    # in the merged fleet trace (a router-driven resubmit overrides
    # both with a fresh migration-hop span).
    tr = getattr(st, "trace", None)
    trace_id = (tr.trace_id if tr is not None
                else getattr(req, "trace_id", None))
    if trace_id is not None:
        spec["trace_id"] = str(trace_id)
    for k in ("trace_parent", "trace_process"):
        v = getattr(req, k, None)
        if v is not None:
            spec[k] = str(v)
    return spec


def np_tolist(a):
    return a.tolist() if hasattr(a, "tolist") else list(a)


def save_drain_snapshot(root: str, specs: List[dict]) -> str:
    """Commit ``specs`` as ``<root>/drain_<n>`` via the checkpoint
    atomic-commit protocol: stage, fsync'd manifest, rename. Readers
    (:func:`load_drain_snapshot`) only ever see committed-and-valid
    snapshots; a torn write (chaos ``ckpt.write.torn``) is caught by the
    manifest size check and falls back to the previous snapshot."""
    from ..distributed.checkpoint import STAGING_SUFFIX, _commit
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    n = max((_drain_seq(name) for name in os.listdir(root)), default=0) + 1
    final = os.path.join(root, f"drain_{n}")
    tmp = final + STAGING_SUFFIX
    if os.path.isdir(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    doc = {"format": 1, "created": time.time(),
           "requests": list(specs)}
    _commit(tmp, final, leaves={},
            extra_files={DRAIN_STATE_NAME: json.dumps(doc, indent=1)},
            step=n)
    return final


def _drain_seq(name: str) -> int:
    m = _DRAIN_DIR_RE.match(name)
    return int(m.group(1)) if m else 0


def load_drain_snapshot(root: str) \
        -> Tuple[Optional[str], List[dict]]:
    """Newest *valid* drain snapshot under ``root`` → ``(path, specs)``,
    or ``(None, [])``. Torn/uncommitted snapshot dirs are skipped with a
    ``checkpoint_fallback`` flight event — the same reader discipline as
    checkpoint resume."""
    from ..distributed.checkpoint import verify_checkpoint
    from ..monitor.flight_recorder import safe_record_event
    if not os.path.isdir(root):
        return None, []
    seqs = sorted((_drain_seq(name) for name in os.listdir(root)
                   if _DRAIN_DIR_RE.match(name)), reverse=True)
    for n in seqs:
        path = os.path.join(root, f"drain_{n}")
        reason = verify_checkpoint(path)
        if reason is None:
            try:
                with open(os.path.join(path, DRAIN_STATE_NAME)) as f:
                    doc = json.load(f)
                return path, list(doc.get("requests") or [])
            except (OSError, ValueError) as e:
                reason = f"drain state unreadable: {e!r}"
        logger.warning("drain restore: skipping %s: %s", path, reason)
        safe_record_event("checkpoint_fallback", step=n, reason=reason,
                          kind="drain_snapshot")
    return None, []


def requests_from_snapshot(specs: List[dict]) -> List[object]:
    """Rebuild submittable :class:`~.scheduler.Request` objects from
    snapshot specs: the effective prompt (original + generated-so-far)
    with the remaining token budget, so a greedy request continues its
    stream token-exactly."""
    from .sampling import SamplingParams
    from .scheduler import Request
    out = []
    for d in specs:
        generated = list(d.get("generated") or [])
        remaining = int(d["max_new_tokens"]) - len(generated)
        if remaining < 1:
            continue                    # nothing left undone
        out.append(Request(
            list(d["prompt"]) + generated,
            max_new_tokens=remaining,
            sampling=SamplingParams(**(d.get("sampling") or {})),
            eos_token_id=d.get("eos_token_id"),
            priority=int(d.get("priority", 0)),
            trace_id=d.get("trace_id"),
            trace_parent=d.get("trace_parent"),
            trace_process=d.get("trace_process")))
    return out
