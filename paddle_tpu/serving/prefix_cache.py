"""Radix-tree prefix cache over the paged KV pools (ISSUE 15).

The SGLang RadixAttention idea on the PR 6 page substrate: K/V a
finished request computed for its prompt is a reusable artifact, not
garbage — chat traffic re-sends the same system prompt thousands of
times, and every byte of that prefix's K/V is identical across
requests. This module keeps donated pages in a token-keyed radix tree
at PAGE granularity:

- every edge of the tree is one FULL page, keyed by the exact
  ``block_size``-token tuple it holds — page granularity is what makes
  sharing free on device: a cached page maps into a new slot's block
  table as-is (one int), no copy, no kernel change;
- **donation** (``free_slot(donate_tokens=...)``): when a request
  terminates or is preempted, its full pages walk into the tree —
  ownership of the slot's page reference transfers to the tree, paths
  already cached drop the duplicate — so the tree is populated by
  traffic itself, no warmup pass;
- **match** (admission): the new request's effective prompt walks the
  tree; every hit page is ``incref``'d and mapped **copy-on-write**
  into the slot's table head (the slot never writes positions below
  the shared coverage — prefill starts at the hit length), and the
  engine prefills ONLY the tail. A partial-page tail is re-prefilled:
  sub-page sharing would need an in-page token count per table entry
  in the device program, which buys little at block_size 16-32;
- **eviction**: LRU over leaf pages, triggered by allocation pressure
  (``PagedKVCache._alloc``) BEFORE any recompute-preemption — a cached
  prefix is strictly cheaper to lose than a live request's progress.
  Eviction drops the tree's reference; a page still mapped by live
  slots stays allocated until they finish (the refcount contract).

The match result is always capped one token short of the query: the
engine must prefill at least the LAST prompt token to have a logits
row to sample the first output token from.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["RadixPrefixCache"]


class _Node:
    """One cached page: the edge from ``parent`` keyed by the
    ``block_size``-token tuple whose K/V the page holds."""

    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.page = int(page)
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0


class RadixPrefixCache:
    """Token-keyed radix tree of donated KV pages with LRU eviction.

    Owns ONE allocator reference per resident page; slots that map a
    cached page hold their own references on top (``incref`` at match
    time), so eviction and slot lifetime compose without coordination.
    Host-side stats accumulate in ``self.stats`` — the ENGINE publishes
    them to the registry (delta publishing, the scheduler-never-writes
    convention).
    """

    def __init__(self, cache):
        self.cache = cache                    # PagedKVCache
        self.block_size = int(cache.block_size)
        self._root = _Node((), -1, None)
        self._nodes: Dict[int, _Node] = {}    # page -> node
        self._tick = 0
        self.stats = {"hits": 0, "misses": 0, "hit_tokens": 0,
                      "hit_pages": 0, "donated_pages": 0,
                      "evicted_pages": 0, "lookups": 0}

    # -- introspection -------------------------------------------------------
    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_used = self._tick

    # -- admission-side ------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached page-aligned prefix of ``tokens`` →
        ``(n_tokens, pages)``. Every returned page is ``incref``'d for
        the caller (the slot mapping it); the hit is capped at
        ``len(tokens) - 1`` so at least one token remains to prefill.
        An empty result means a full cold prefill."""
        bs = self.block_size
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        max_blocks = max(0, (len(toks) - 1) // bs)
        node = self._root
        pages: List[int] = []
        for i in range(max_blocks):
            child = node.children.get(tuple(toks[i * bs:(i + 1) * bs]))
            if child is None:
                break
            self._touch(child)
            pages.append(child.page)
            node = child
        self.stats["lookups"] += 1
        if pages:
            for p in pages:
                self.cache.allocator.incref(p)
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(pages) * bs
            self.stats["hit_pages"] += len(pages)
        else:
            self.stats["misses"] += 1
        return len(pages) * bs, pages

    # -- donation ------------------------------------------------------------
    def donate(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Walk ``tokens``' full pages into the tree, CONSUMING the
        caller's reference on each consumed page (kept for a new node,
        dropped for a path already cached). Returns how many leading
        entries of ``pages`` were consumed — the caller frees the rest
        (the partial tail and anything beyond the valid token count)."""
        bs = self.block_size
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        full = min(len(toks) // bs, len(pages))
        node = self._root
        for i in range(full):
            key = tuple(toks[i * bs:(i + 1) * bs])
            page = int(pages[i])
            child = node.children.get(key)
            if child is None:
                child = _Node(key, page, node)
                node.children[key] = child
                self._nodes[page] = child
                self.stats["donated_pages"] += 1
            else:
                # the path is already cached under a (possibly
                # different) physical page — drop the duplicate ref
                self.cache.allocator.free([page])
            self._touch(child)
            node = child
        return full

    # -- eviction ------------------------------------------------------------
    def evict_for(self, n_pages: int) -> int:
        """Drop LRU leaf pages until at least ``n_pages`` re-entered
        the allocator free list or the tree is empty. Returns the pages
        actually RETURNED to the free list (a page still mapped by a
        live slot leaves the tree but stays allocated — it contributes
        0 here and frees when its slots do).

        One leaf heap is built per call and parents join it as their
        last child leaves — O((leaves + evicted)·log n), so an eviction
        storm inside the admission path never rescans the whole tree
        per page. Nothing touches ``last_used`` mid-call (the serving
        loop is single-threaded), so the snapshot order stays valid."""
        import heapq
        freed = 0
        alloc = self.cache.allocator
        heap = [(node.last_used, node.page)
                for node in self._nodes.values() if not node.children]
        heapq.heapify(heap)
        while heap and freed < max(n_pages, 1):
            _, page = heapq.heappop(heap)
            leaf = self._nodes.get(page)
            if leaf is None or leaf.children:
                continue
            del self._nodes[page]
            parent = leaf.parent
            del parent.children[leaf.key]
            if parent is not self._root and not parent.children:
                heapq.heappush(heap, (parent.last_used, parent.page))
            before = alloc.free_pages
            alloc.free([page])
            freed += alloc.free_pages - before
            self.stats["evicted_pages"] += 1
        return freed

    def clear(self) -> int:
        """Drop every cached page (engine shutdown). Returns the count
        dropped."""
        n = len(self._nodes)
        for node in list(self._nodes.values()):
            self.cache.allocator.free([node.page])
        self._nodes.clear()
        self._root.children.clear()
        return n
