"""paddle_tpu.serving — TPU-native inference serving runtime (ISSUE 6).

The path from a trained model to traffic (ROADMAP item 1, the
millions-of-users north star), built on two ideas from the serving
literature mapped onto static-shape XLA programs:

- **paged KV decode** (:mod:`.kv_cache`): block-structured K/V pools
  shared by all requests with per-slot block tables — the
  vLLM/PagedAttention memory model, generalized from ``StaticCache`` so
  it composes with scan-over-layers
  (``nn.scan.scan_layers_with_cache``, ``FLAGS_scan_decode``);
- **continuous batching** (:mod:`.scheduler`): iteration-level
  admission/eviction into fixed batch slots (Orca), with bucketed
  ``(batch, prefill_len)`` prefill shapes bounding the compile count
  and recompute-preemption when the page pool runs dry;
- the :class:`~.engine.ServingEngine` glues them behind AOT-compiled
  serving signatures (``jit.aot.AOTProgram``, the TrainStep machinery),
  streaming per-token callbacks and TTFT/TPOT/throughput metrics into
  the :mod:`paddle_tpu.monitor` registry;
- :mod:`.loadgen` is the synthetic open-loop driver behind
  ``bench.py --serve`` (the ``BENCH_serve`` record);
- :mod:`.router` scales one engine to a fleet (ISSUE 16): a
  prefix-affine front-end over N replicas with telemetry-driven load
  balancing and chaos-proof drain/death migration;
- :mod:`.lifecycle` pushes new weights through that fleet with zero
  downtime (ISSUE 20): live hot-swap with per-slot weight epochs
  (:meth:`~.engine.ServingEngine.swap_weights`), shadow/A-B traffic
  splitting, and an SLO-guarded promote-or-rollback controller.

See docs/SERVING.md for architecture, bucketing policy, the flag
matrix and the fleet topology.
"""

from .detok import StreamingDetokenizer  # noqa: F401
from .engine import (ServingConfig, ServingEngine,  # noqa: F401
                     WeightSwapError)
from .lifecycle import (LifecycleConfig, LifecycleController,  # noqa: F401
                        TrafficSplit, assign_arm, should_shadow)
from .kv_cache import (BlockAllocator, ContextPagedCacheView,  # noqa: F401
                       ContextPagedLayerCache, PagedCacheView,
                       PagedKVCache, PagedLayerCache)
from .prefix_cache import RadixPrefixCache  # noqa: F401
from .spec_decode import propose_ngram  # noqa: F401
from .loadgen import (LoadSpec, TokenBucket, build_requests,  # noqa: F401
                      run_fleet_open_loop, run_open_loop)
from .router import FleetRouter, ReplicaHandle, RouterConfig  # noqa: F401
from .resilience import (DecodeWatchdogError, DrainLatch,  # noqa: F401
                         DrainReport, EngineDrained, OverloadDetector,
                         ServerOverloaded, load_drain_snapshot,
                         requests_from_snapshot, save_drain_snapshot)
from .sampling import (SamplingParams, filtered_logits,  # noqa: F401
                       sample_tokens)
from .scheduler import (TERMINAL_OUTCOMES, BucketTable,  # noqa: F401
                        Request, Scheduler)

__all__ = [
    "ServingConfig", "ServingEngine", "Request", "SamplingParams",
    "BucketTable", "Scheduler", "PagedKVCache", "PagedCacheView",
    "PagedLayerCache", "BlockAllocator", "StreamingDetokenizer",
    "LoadSpec", "TokenBucket", "build_requests", "run_open_loop",
    "ServerOverloaded", "EngineDrained", "DecodeWatchdogError",
    "DrainLatch", "DrainReport", "OverloadDetector",
    "save_drain_snapshot", "load_drain_snapshot",
    "requests_from_snapshot", "TERMINAL_OUTCOMES", "reset",
    "RadixPrefixCache", "propose_ngram", "ContextPagedCacheView",
    "ContextPagedLayerCache", "FleetRouter", "ReplicaHandle",
    "RouterConfig", "run_fleet_open_loop", "filtered_logits",
    "WeightSwapError", "TrafficSplit", "LifecycleConfig",
    "LifecycleController", "assign_arm", "should_shadow",
]


def reset() -> None:
    """Tear down process-global serving state (conftest autouse): shut
    down live engines — which restores any drain-latch signal handlers
    and joins/abandons live watchdog threads (their chaos hangs are
    cancelled first, so a hung worker cannot outlive its test) — then
    restart the request-id counter and clear the scan-fallback warn-once
    set + counter so fallback-telemetry assertions are
    order-independent."""
    from . import engine as _engine, scheduler as _scheduler
    from ..nn import scan as _scan
    for e in list(_engine._LIVE_ENGINES):
        try:
            e.shutdown()
        except Exception:
            pass
    _engine._LIVE_ENGINES.clear()
    _scheduler._reset_request_ids()
    _scan.SCAN_STATS["fallbacks"] = 0
    _scan._FALLBACK_WARNED.clear()
