"""Self-drafting speculative decoding: n-gram prompt-lookup proposals
verified in one batched dispatch (ISSUE 15).

Leviathan et al. (2023) speculative decoding needs a cheap drafter and
an exact verifier. The verifier here is the serving model itself — ONE
context-prefill-shaped dispatch scores all ``k+1`` positions of
``[last_token, d_1 .. d_k]`` against the paged cache, so accepted
tokens cost ``1/(n_acc+1)`` dispatches each. The drafter is
**prompt-lookup** (Saxena 2023 / transformers' assisted generation):
propose the continuation of the most recent earlier occurrence of the
sequence's own trailing n-gram. No second model, no extra weights, no
device work — and LLM output is self-repetitive exactly where decoding
is slowest (code, structured data, quoted context, chat boilerplate).

Greedy acceptance is exact by construction: draft ``d_i`` is accepted
iff it equals the verifier's argmax at position ``i-1``, so the
committed stream is the token-for-token greedy output of the plain
decode loop (pinned by test). The engine only drafts for greedy slots;
sampled slots ride the verify dispatch's row 0 as a plain decode step.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["propose_ngram"]


def propose_ngram(tokens: Sequence[int], k: int, max_ngram: int = 3,
                  min_ngram: int = 1) -> np.ndarray:
    """Up to ``k`` draft tokens continuing ``tokens`` by prompt lookup.

    Tries the longest trailing n-gram first (``max_ngram`` down to
    ``min_ngram``): if it occurred earlier in ``tokens``, the tokens
    that FOLLOWED its most recent earlier occurrence are the draft.
    Returns an empty array when no n-gram recurs — the slot decodes
    plainly this iteration (zero wasted compute, the drafter is free).
    """
    toks = np.asarray(tokens, np.int64).reshape(-1)
    T = toks.size
    if k <= 0 or T < min_ngram + 1:
        return np.zeros((0,), np.int32)
    for n in range(min(max_ngram, T - 1), min_ngram - 1, -1):
        suffix = toks[T - n:]
        # windows [i, i+n) for i in 0..T-n-1: every PRIOR occurrence
        # (the trailing window itself is excluded)
        win = np.lib.stride_tricks.sliding_window_view(toks, n)[:T - n]
        hits = np.flatnonzero((win == suffix).all(axis=1))
        if hits.size == 0:
            continue
        start = int(hits[-1]) + n          # most recent occurrence
        draft = toks[start:start + k]
        if draft.size:
            return draft.astype(np.int32)
    return np.zeros((0,), np.int32)
