"""Streaming detokenization for per-token callbacks.

The engine surfaces every sampled token to the request's ``on_token``
callback the step it is produced; when the engine is built with a
detokenizer, the callback also receives the incremental TEXT piece so a
chat front end can render as tokens arrive (reference analogue: the
FasterTokenizer vocab of ``paddle_tpu.text``, read in reverse).

Wordpiece convention: a ``##``-prefixed piece glues to the previous one,
anything else starts a new whitespace-separated word. Unknown ids render
as ``[UNK:<id>]`` rather than dropping silently.
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence, Union

__all__ = ["StreamingDetokenizer"]


class StreamingDetokenizer:
    """Incremental id→text converter. Stateless per call: the caller says
    whether this is the first piece of the stream."""

    def __init__(self, vocab: Union[Sequence[str], Mapping[str, int]]):
        if isinstance(vocab, Mapping):
            self._id_to_token: Dict[int, str] = {
                int(i): t for t, i in vocab.items()}
        else:
            self._id_to_token = dict(enumerate(vocab))

    def piece(self, token_id: int, is_first: bool) -> str:
        tok = self._id_to_token.get(int(token_id))
        if tok is None:
            tok = f"[UNK:{int(token_id)}]"
        if tok.startswith("##"):
            return tok[2:]
        return tok if is_first else " " + tok

    def decode(self, token_ids: Sequence[int]) -> str:
        return "".join(self.piece(t, i == 0)
                       for i, t in enumerate(token_ids))
