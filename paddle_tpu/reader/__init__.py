"""Legacy reader decorators (reference: python/paddle/reader/decorator.py:
map_readers, shuffle, chain, compose, buffered, firstn, cache,
xmap_readers). Pure-python composition utilities over sample generators;
kept for migrating reference data pipelines (new code: paddle.io)."""

from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "cache", "xmap_readers"]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def chained():
        yield from itertools.chain(*[r() for r in readers])
    return chained


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        for parts in (zip(*rs) if check_alignment
                      else itertools.zip_longest(*rs)):
            yield sum((make_tuple(p) for p in parts), ())
    return composed


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples."""
    end = object()

    def buffered_reader():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader


def cache(reader):
    all_data = None

    def cached():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data
    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool map over a reader (reference keeps sample order only
    when order=True)."""
    from concurrent.futures import ThreadPoolExecutor

    def xreader():
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            it = reader()
            if order:
                yield from pool.map(mapper, it)
            else:
                futures = []
                for sample in it:
                    futures.append(pool.submit(mapper, sample))
                    if len(futures) >= buffer_size:
                        done = futures.pop(0)
                        yield done.result()
                for f in futures:
                    yield f.result()
    return xreader
