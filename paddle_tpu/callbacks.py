"""paddle.callbacks namespace (reference: python/paddle/callbacks.py
re-exporting the hapi callback family)."""

from .hapi.callbacks import *  # noqa: F401,F403
from .hapi.callbacks import __all__  # noqa: F401
